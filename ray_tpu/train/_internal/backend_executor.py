"""BackendExecutor: drives the worker gang through a training run.

Reference: `python/ray/train/_internal/backend_executor.py:43` (`BackendExecutor`),
`start:94`, `_create_placement_group:147`, `start_training:325`,
`get_next_results:426`. Gang semantics are all-or-nothing (SURVEY.md §7 "SPMD
gang semantics"): any worker failure fails the whole group; the trainer layer
restarts the full gang from the last checkpoint.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train._internal.session import DONE, ERROR, REPORT, SessionArgs, TrainingResult
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig
from ray_tpu.util.placement_group import placement_group, remove_placement_group


class TrainingWorkerError(Exception):
    """A worker of the gang failed; the gang must be restarted as a unit."""


def _rendezvous_wait_total() -> float:
    """Runs on a worker: process-lifetime seconds blocked in collective
    rendezvous (includes jax.distributed.initialize gang-join)."""
    from ray_tpu.util.collective import rendezvous

    return float(rendezvous._WAIT_STATS["wait_s"])


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        trial_info: Optional[Dict[str, str]] = None,
        gang_id: str = "",
        ledger=None,
    ):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._trial_info = trial_info or {}
        self._gang_id = gang_id or self._trial_info.get("trial_id") or "default"
        self._ledger = ledger  # GoodputLedger (driver-owned) or None
        self._pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self._ranks: List[int] = []
        # Straggler hysteresis: when the per-round skew first breached, and
        # whether the sustained-breach event already fired for this episode.
        self._skew_breach_since: Optional[float] = None
        self._skew_event_sent = False
        self._skew_gauge_touched = False

    # ------------------------------------------------------------------ start
    def start(self):
        bundles = self._scaling.as_placement_group_bundles()
        self._pg = placement_group(bundles, strategy=self._scaling.placement_strategy)
        if not self._pg.ready(timeout=60.0):
            remove_placement_group(self._pg)
            self._pg = None
            raise TrainingWorkerError(
                f"placement group {bundles} not schedulable on this cluster"
            )
        try:
            self.worker_group = WorkerGroup(
                self._scaling.num_workers,
                resources_per_worker=self._scaling._resources,
                placement_group=self._pg,
            )
            meta = self.worker_group.fetch_metadata()
        except Exception as e:
            # Worker/actor death during gang bring-up must consume the
            # FailureConfig budget (gang restart), not surface as a
            # driver-side bug (reference retries startup failures too).
            raise TrainingWorkerError(f"gang startup failed: {e}") from e
        # Rank assignment: stable by (node ip, pid) so local ranks are contiguous
        # per node (the reference sorts workers by node for the same reason).
        order = sorted(range(len(meta)), key=lambda i: (meta[i].node_ip, meta[i].pid))
        self._ranks = [order.index(i) for i in range(len(meta))]
        self._local: List[Dict[str, int]] = [{} for _ in meta]
        by_node: Dict[str, List[int]] = {}
        for i in order:
            by_node.setdefault(meta[i].node_ip, []).append(i)
        node_ips = sorted(by_node)
        for node_rank, ip in enumerate(node_ips):
            for local_rank, i in enumerate(by_node[ip]):
                self._local[i] = {
                    "local_rank": local_rank,
                    "local_world_size": len(by_node[ip]),
                    "node_rank": node_rank,
                }
        try:
            self._backend.on_start(self, self._backend_config)
        except RayTpuError as e:
            raise TrainingWorkerError(f"gang startup failed: {e}") from e

    @property
    def ranks(self) -> List[int]:
        return list(self._ranks)

    def world_info(self, worker_index: int) -> Dict[str, int]:
        info = dict(self._local[worker_index])
        info["world_rank"] = self._ranks[worker_index]
        info["world_size"] = len(self._ranks)
        return info

    # --------------------------------------------------------------- training
    def start_training(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        config: Dict[str, Any],
        checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[List[Dict[str, Any]]] = None,
        mesh_builder: Optional[Callable] = None,
    ):
        try:
            self._backend.on_training_start(self, self._backend_config)
        except RayTpuError as e:
            raise TrainingWorkerError(f"gang startup failed: {e}") from e
        refs = []
        for i, w in enumerate(self.worker_group.workers):
            info = self.world_info(i)
            args = SessionArgs(
                train_fn=train_fn,
                config=dict(config),
                world_rank=info["world_rank"],
                world_size=info["world_size"],
                local_rank=info["local_rank"],
                local_world_size=info["local_world_size"],
                node_rank=info["node_rank"],
                checkpoint=checkpoint,
                dataset_shards=(dataset_shards or [{}] * len(self._ranks))[
                    info["world_rank"]
                ],
                mesh_builder=mesh_builder,
                gang_id=self._gang_id,
                **self._trial_info,
            )
            refs.append(w.init_session.remote(args))
        try:
            ray_tpu.get(refs)
        except Exception as e:
            raise TrainingWorkerError(f"gang startup failed: {e}") from e

    def gang_rendezvous_seconds(self) -> float:
        """Gang-mean seconds the workers spent blocked in rendezvous so far
        (the ledger's rendezvous_wait share of bring-up). Best-effort: 0.0
        when observability is off or the gang is unreachable."""
        from ray_tpu._private.telemetry import metrics_enabled

        if not metrics_enabled() or self.worker_group is None:
            return 0.0
        try:
            totals = self.worker_group.execute(_rendezvous_wait_total)
        except Exception:  # noqa: BLE001 — dying gang; caller handles failure
            return 0.0
        return sum(totals) / len(totals) if totals else 0.0

    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One result per worker (ordered by world rank), or None when all DONE.

        Raises TrainingWorkerError if any worker errored or died.
        """
        refs = [w.next_result.remote() for w in self.worker_group.workers]
        try:
            results: List[TrainingResult] = ray_tpu.get(refs)
        except Exception as e:
            raise TrainingWorkerError(f"a training worker died: {e}") from e
        by_rank = sorted(results, key=lambda r: r.world_rank)
        errors = [r for r in by_rank if r.type == ERROR]
        if errors:
            raise TrainingWorkerError(
                "training worker(s) failed:\n" + "\n".join(r.error for r in errors)
            )
        if all(r.type == DONE for r in by_rank):
            return None
        if any(r.type != REPORT for r in by_rank):
            # Mixed DONE/REPORT: some worker returned early — a gang bug.
            raise TrainingWorkerError(
                "workers out of sync: mixed DONE and REPORT results in one round"
            )
        self._fold_results(by_rank)
        return by_rank

    def _fold_results(self, by_rank: List[TrainingResult]) -> None:
        """Per-round observability fold: gang skew gauge, straggler naming
        (slowest rank + its dominant phase excess over the gang mean), the
        sustained-breach train_straggler event, and the goodput ledger."""
        pairs = [(r.world_rank, r.telemetry) for r in by_rank if r.telemetry]
        straggler = None
        skew = 0.0
        per_rank: Dict[str, Dict[str, Any]] = {}
        if len(pairs) == len(by_rank) and len(pairs) >= 2:
            # Skew is computed on ACTIVE time, not raw step wall: the gang
            # runs lockstep (bounded result queue + collectives), so every
            # rank's wall converges to the slowest rank's. Waiting-for-others
            # time — report-queue backpressure and collective arrival offset
            # (how early this rank reached the rendezvous) — is subtracted;
            # what's left is the rank's own work, where a straggler shows.
            walls = {}
            for rk, t in pairs:
                wait = (t.get("phases") or {}).get("report", 0.0) + float(
                    t.get("arrival_offset_s", 0.0)
                )
                walls[rk] = max(0.0, float(t.get("step_wall_s", 0.0)) - wait)
            slow = max(walls, key=walls.get)
            skew = walls[slow] - min(walls.values())
            n = len(pairs)
            means: Dict[str, float] = {}
            for _, t in pairs:
                for p, v in (t.get("phases") or {}).items():
                    means[p] = means.get(p, 0.0) + v / n
            slow_phases = dict(
                next(t for rk, t in pairs if rk == slow).get("phases") or {}
            )
            excess = {
                p: slow_phases.get(p, 0.0) - means.get(p, 0.0)
                for p in set(slow_phases) | set(means)
            }
            dominant = max(excess, key=excess.get) if excess else "step_exec"
            straggler = {
                "rank": slow,
                "phase": dominant,
                "skew_s": round(skew, 6),
                "active_s": round(walls[slow], 6),
            }
            per_rank = {
                str(rk): {
                    "step_wall_s": round(float(t.get("step_wall_s", 0.0)), 6),
                    "phases": {
                        p: round(v, 6)
                        for p, v in (t.get("phases") or {}).items()
                    },
                }
                for rk, t in pairs
            }
            from ray_tpu._private.telemetry import metrics_enabled, train_metrics

            if metrics_enabled():
                train_metrics()["step_skew"].set(skew, {"gang": self._gang_id})
                self._skew_gauge_touched = True
            from ray_tpu._private.config import get_config

            cfg = get_config()
            if skew > cfg.train_straggler_skew_s:
                now = time.monotonic()
                if self._skew_breach_since is None:
                    self._skew_breach_since = now
                elif (
                    not self._skew_event_sent
                    and now - self._skew_breach_since >= cfg.train_straggler_for_s
                ):
                    from ray_tpu._private.events import emit_event

                    emit_event(
                        "train_straggler",
                        f"gang {self._gang_id}: rank {slow} is straggling "
                        f"(skew {skew:.3f}s, dominant phase {dominant})",
                        severity="warning",
                        source="train-driver",
                        gang=self._gang_id,
                        rank=slow,
                        phase=dominant,
                        skew_s=round(skew, 6),
                    )
                    self._skew_event_sent = True
            else:
                self._skew_breach_since = None
                self._skew_event_sent = False
        if self._ledger is not None:
            self._ledger.note_skew(skew, straggler, per_rank)
            self._ledger.fold_round([t for _, t in pairs])

    # ---------------------------------------------------------------- shutdown
    def shutdown(self):
        if self._skew_gauge_touched:
            # The driver registry re-flushes a gauge's last value forever;
            # left non-zero after the gang ends, the train_straggler alert
            # would never resolve. Park it at 0 explicitly.
            try:
                from ray_tpu._private.telemetry import train_metrics

                train_metrics()["step_skew"].set(0.0, {"gang": self._gang_id})
            except Exception:  # noqa: BLE001
                pass
            self._skew_gauge_touched = False
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
