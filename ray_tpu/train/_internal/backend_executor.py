"""BackendExecutor: drives the worker gang through a training run.

Reference: `python/ray/train/_internal/backend_executor.py:43` (`BackendExecutor`),
`start:94`, `_create_placement_group:147`, `start_training:325`,
`get_next_results:426`. Gang semantics are all-or-nothing (SURVEY.md §7 "SPMD
gang semantics"): any worker failure fails the whole group; the trainer layer
restarts the full gang from the last checkpoint.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train._internal.session import DONE, ERROR, REPORT, SessionArgs, TrainingResult
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig
from ray_tpu.util.placement_group import placement_group, remove_placement_group


class TrainingWorkerError(Exception):
    """A worker of the gang failed; the gang must be restarted as a unit."""


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        trial_info: Optional[Dict[str, str]] = None,
    ):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._trial_info = trial_info or {}
        self._pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self._ranks: List[int] = []

    # ------------------------------------------------------------------ start
    def start(self):
        bundles = self._scaling.as_placement_group_bundles()
        self._pg = placement_group(bundles, strategy=self._scaling.placement_strategy)
        if not self._pg.ready(timeout=60.0):
            remove_placement_group(self._pg)
            self._pg = None
            raise TrainingWorkerError(
                f"placement group {bundles} not schedulable on this cluster"
            )
        try:
            self.worker_group = WorkerGroup(
                self._scaling.num_workers,
                resources_per_worker=self._scaling._resources,
                placement_group=self._pg,
            )
            meta = self.worker_group.fetch_metadata()
        except Exception as e:
            # Worker/actor death during gang bring-up must consume the
            # FailureConfig budget (gang restart), not surface as a
            # driver-side bug (reference retries startup failures too).
            raise TrainingWorkerError(f"gang startup failed: {e}") from e
        # Rank assignment: stable by (node ip, pid) so local ranks are contiguous
        # per node (the reference sorts workers by node for the same reason).
        order = sorted(range(len(meta)), key=lambda i: (meta[i].node_ip, meta[i].pid))
        self._ranks = [order.index(i) for i in range(len(meta))]
        self._local: List[Dict[str, int]] = [{} for _ in meta]
        by_node: Dict[str, List[int]] = {}
        for i in order:
            by_node.setdefault(meta[i].node_ip, []).append(i)
        node_ips = sorted(by_node)
        for node_rank, ip in enumerate(node_ips):
            for local_rank, i in enumerate(by_node[ip]):
                self._local[i] = {
                    "local_rank": local_rank,
                    "local_world_size": len(by_node[ip]),
                    "node_rank": node_rank,
                }
        try:
            self._backend.on_start(self, self._backend_config)
        except RayTpuError as e:
            raise TrainingWorkerError(f"gang startup failed: {e}") from e

    @property
    def ranks(self) -> List[int]:
        return list(self._ranks)

    def world_info(self, worker_index: int) -> Dict[str, int]:
        info = dict(self._local[worker_index])
        info["world_rank"] = self._ranks[worker_index]
        info["world_size"] = len(self._ranks)
        return info

    # --------------------------------------------------------------- training
    def start_training(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        config: Dict[str, Any],
        checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[List[Dict[str, Any]]] = None,
        mesh_builder: Optional[Callable] = None,
    ):
        try:
            self._backend.on_training_start(self, self._backend_config)
        except RayTpuError as e:
            raise TrainingWorkerError(f"gang startup failed: {e}") from e
        refs = []
        for i, w in enumerate(self.worker_group.workers):
            info = self.world_info(i)
            args = SessionArgs(
                train_fn=train_fn,
                config=dict(config),
                world_rank=info["world_rank"],
                world_size=info["world_size"],
                local_rank=info["local_rank"],
                local_world_size=info["local_world_size"],
                node_rank=info["node_rank"],
                checkpoint=checkpoint,
                dataset_shards=(dataset_shards or [{}] * len(self._ranks))[
                    info["world_rank"]
                ],
                mesh_builder=mesh_builder,
                **self._trial_info,
            )
            refs.append(w.init_session.remote(args))
        try:
            ray_tpu.get(refs)
        except Exception as e:
            raise TrainingWorkerError(f"gang startup failed: {e}") from e

    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One result per worker (ordered by world rank), or None when all DONE.

        Raises TrainingWorkerError if any worker errored or died.
        """
        refs = [w.next_result.remote() for w in self.worker_group.workers]
        try:
            results: List[TrainingResult] = ray_tpu.get(refs)
        except Exception as e:
            raise TrainingWorkerError(f"a training worker died: {e}") from e
        by_rank = sorted(results, key=lambda r: r.world_rank)
        errors = [r for r in by_rank if r.type == ERROR]
        if errors:
            raise TrainingWorkerError(
                "training worker(s) failed:\n" + "\n".join(r.error for r in errors)
            )
        if all(r.type == DONE for r in by_rank):
            return None
        if any(r.type != REPORT for r in by_rank):
            # Mixed DONE/REPORT: some worker returned early — a gang bug.
            raise TrainingWorkerError(
                "workers out of sync: mixed DONE and REPORT results in one round"
            )
        return by_rank

    # ---------------------------------------------------------------- shutdown
    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
