"""Per-worker training session: runs the user loop in a thread and streams
results to the driver.

Reference: `python/ray/train/_internal/session.py` (the thread-based
`_TrainSession`): `session.report` enqueues a `TrainingResult`; the driver's
`BackendExecutor.get_next_results` round-robins `next_result()` across the
gang. The queue is bounded at 1 so training naturally backpressures on the
driver consuming results (and a checkpoint is fully handed off before the
loop continues — the property PBT-style mutation relies on).
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint

REPORT = "report"
DONE = "done"
ERROR = "error"
# The session was stopped at a step boundary by an elastic drain — not an
# error, not a completion. Emitted to unblock any in-flight next_result.
DRAINED = "drained"


class SessionDrained(BaseException):
    """Raised inside `session.report` when the driver drained this rank at a
    step boundary (elastic resize). Derives from BaseException so a user
    loop's `except Exception` cannot swallow the gang's stop request."""


@dataclass
class TrainingResult:
    type: str  # REPORT | DONE | ERROR
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    world_rank: int = 0
    # Step-clock payload (train/_internal/telemetry.py): per-step phase split
    # on REPORT, cumulative totals on DONE. None when observability is off.
    telemetry: Optional[Dict[str, Any]] = None


@dataclass
class SessionArgs:
    train_fn: Callable
    config: Dict[str, Any]
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    trial_name: str = ""
    trial_id: str = ""
    trial_dir: str = ""
    experiment_name: str = ""
    checkpoint: Optional[Checkpoint] = None
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    mesh_builder: Optional[Callable] = None  # () -> jax Mesh, run in-thread
    # Stable id shared by every rank (and every restart) of one fit() — the
    # `gang` tag on train metrics and the training_report KV key.
    gang_id: str = ""


class _TrainSession:
    def __init__(self, args: SessionArgs):
        self.args = args
        self.world_rank = args.world_rank
        self.world_size = args.world_size
        self.local_rank = args.local_rank
        self.local_world_size = args.local_world_size
        self.node_rank = args.node_rank
        self.trial_name = args.trial_name
        self.trial_id = args.trial_id
        self.trial_dir = args.trial_dir
        self.experiment_name = args.experiment_name
        self.loaded_checkpoint = args.checkpoint
        self.dataset_shards = args.dataset_shards
        self.gang_id = args.gang_id or args.trial_id or "default"
        self.mesh = None
        self._clock = None  # StepClock, built in-thread by _run
        self._q: "queue.Queue[TrainingResult]" = queue.Queue(maxsize=1)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._finished = threading.Event()
        # Elastic drain: set by the driver (via the worker actor) to stop the
        # loop at the next step boundary; `drained` records a clean stop.
        self._stop = threading.Event()
        self.drained = False
        self._reported_steps = 0

    # ----------------------------------------------------------- thread side
    def _run(self):
        from ray_tpu.train._internal.telemetry import make_clock

        air_session._set_session(self)
        try:
            # Built here, not in __init__: the train_step span must live in
            # this thread so collective spans auto-parent under it.
            self._clock = make_clock(self.gang_id, self.world_rank)
            if self.args.mesh_builder is not None:
                if self._clock is not None:
                    self._clock.mark("compile")
                self.mesh = self.args.mesh_builder()
                if self._clock is not None:
                    self._clock.mark("step_exec")
            self.args.train_fn(self.args.config)
            done = TrainingResult(DONE, world_rank=self.world_rank)
            if self._clock is not None:
                totals = self._clock.finalize()
                if self._clock.metrics_on:
                    done.telemetry = totals
                    # The driver kills gang workers right after DONE — don't
                    # let a short run's step samples die in the 1 Hz flusher.
                    try:
                        from ray_tpu.util.metrics import flush_metrics

                        flush_metrics()
                    except Exception:  # noqa: BLE001
                        pass
            self._q.put(done)
        except SessionDrained:
            # Elastic stop at a step boundary: clean, no result to forward
            # (the driver is not reading this queue any more — it is mid
            # resize and will re-init the session on the re-formed gang).
            self.drained = True
            if self._clock is not None:
                self._clock.finalize()
        except BaseException as e:  # noqa: BLE001 - forwarded to the driver
            if self._clock is not None:
                self._clock.finalize()
            self._q.put(
                TrainingResult(
                    ERROR,
                    error=f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
                    world_rank=self.world_rank,
                )
            )
        finally:
            self._finished.set()
            air_session._set_session(None)

    def mark_phase(self, phase: str) -> None:
        """Explicit phase seam from the user loop (air.session.mark_phase).
        No-op when observability is off — marking costs nothing then."""
        if self._clock is not None:
            self._clock.mark(phase)

    def report(self, metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None):
        from ray_tpu._private import failpoints

        if self._stop.is_set():
            raise SessionDrained()
        if failpoints.ENABLED:
            # Injection point for straggler (delay) and mid-step crash
            # (recover accounting) scenarios: fires on the session thread
            # with the step still open, like a real slow/dying rank.
            failpoints.maybe_crash("train.step")
        result = TrainingResult(
            REPORT, metrics=dict(metrics), checkpoint=checkpoint,
            world_rank=self.world_rank,
        )
        clock = self._clock
        if clock is None:
            self._q.put(result)
            self._reported_steps += 1
            if self._stop.is_set():
                raise SessionDrained()
            return
        telem = clock.close_step(checkpoint=checkpoint is not None)
        if clock.metrics_on:
            result.telemetry = telem
        # The bounded-queue put is driver backpressure: accrue it as the
        # report (or checkpoint) phase of the step now opening.
        clock.mark("checkpoint" if checkpoint is not None else "report")
        try:
            self._q.put(result)
        finally:
            clock.mark("step_exec")
        self._reported_steps += 1
        # Second seam: the drain request may have landed while this thread
        # was blocked in the bounded-queue put above.
        if self._stop.is_set():
            raise SessionDrained()

    def stash_checkpoint(self, state: Any, *, rules=None,
                         step: Optional[int] = None) -> None:
        """In-memory checkpoint stash + peer mirror (elastic recovery). The
        state is snapshot to host numpy; the mirror push is fire-and-forget
        (see train/_internal/elastic.py)."""
        from ray_tpu.air.checkpoint import _tree_to_host
        from ray_tpu.train._internal import elastic

        elastic.stash(
            rank=self.world_rank,
            step=self._reported_steps if step is None else int(step),
            world_size=self.world_size,
            state=_tree_to_host(state),
            rules=rules,
        )

    # ----------------------------------------------------------- driver side
    def start(self):
        self._thread.start()

    def next_result(self, timeout: Optional[float] = None) -> TrainingResult:
        # Polling get, not a bare blocking get: a drained session puts nothing
        # more, and the actor thread parked here must unwind (the driver has
        # abandoned the ref) instead of pinning a concurrency slot forever.
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self._q.get(timeout=0.2)
            except queue.Empty:
                if self._finished.is_set() and self._q.empty():
                    return TrainingResult(DRAINED, world_rank=self.world_rank)
                if deadline is not None and time.monotonic() >= deadline:
                    raise

    def request_stop(self) -> None:
        self._stop.set()

    def drain(self, timeout: float = 10.0) -> bool:
        """Stop the session at the next step boundary and unblock a put-
        blocked report by consuming the queue. Returns True when the loop
        thread actually finished within the timeout (a False return means the
        rank is stuck mid-step — collective hang, very long step — and the
        caller should treat it as dead)."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        while not self._finished.is_set() and time.monotonic() < deadline:
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
        return self._finished.is_set()

    def telemetry_snapshot(self) -> Optional[Dict[str, Any]]:
        """Cumulative phase totals so far (driver-pollable, no step close).
        Benign cross-thread read of monotone floats; None with obs off."""
        clock = self._clock
        if clock is None or not clock.metrics_on:
            return None
        return clock.snapshot()

    def finished(self) -> bool:
        return self._finished.is_set()


# Bound in the worker process by init_session / torn down by shutdown_session.
_session: Optional[_TrainSession] = None


def init_session(args: SessionArgs) -> None:
    global _session
    if _session is not None and not _session.finished():
        raise RuntimeError("a training session is already running in this worker")
    _session = _TrainSession(args)
    _session.start()


def get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError("no training session in this worker")
    return _session


def shutdown_session() -> None:
    global _session
    _session = None
