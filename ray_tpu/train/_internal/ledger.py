"""Goodput ledger: classifies a training gang's wall time into productive
step time vs badput buckets, driver-side.

Every second of a fit() is claimed by exactly one bucket:

  productive      gang-mean step compute + collective time (real training)
  init            first gang bring-up (placement group, actors, backend
                  on_start) minus the rendezvous share below
  compile         gang-mean time in the "compile" phase (cold jit, mesh build)
  rendezvous_wait blocked joining the gang (jax.distributed.initialize,
                  collective KV rendezvous) — from the workers' rendezvous
                  wait accumulators
  checkpoint      gang-mean "checkpoint" phase + driver-side persist
                  (CheckpointManager.register)
  recover         failure detection + full gang restart after a
                  TrainingWorkerError
  resize          elastic membership change: drain at the step boundary,
                  re-rendezvous at the new world size, session re-init
                  (ISSUE 19 — resizes are not failures and not recover)
  idle            everything else: data_wait, report backpressure, driver
                  overhead between rounds

Accounting is interval-chained: the ledger keeps one monotonic mark and every
account_*/fold_round call classifies exactly the wall time since the previous
mark, so the buckets sum to the observed wall time by construction (coverage
~= 1.0; worker-reported phase splits are scaled down if clock skew makes them
exceed the driver-observed interval, never up).

The current report is published to the GCS KV under `train::<gang_id>` so
`state.training_report()`, the dashboard `/api/train`, and
`python -m ray_tpu train` can all read it without new wire plumbing.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

BUCKETS = (
    "productive", "init", "compile", "rendezvous_wait",
    "checkpoint", "recover", "resize", "idle",
)

# Worker step-phase -> ledger bucket for the per-round fold. data_wait and
# report are driver/input-bound, not chip work: badput (idle).
_PHASE_BUCKET = {
    "step_exec": "productive",
    "collective": "productive",
    "compile": "compile",
    "checkpoint": "checkpoint",
    "data_wait": "idle",
    "report": "idle",
}

# Publish throttle: at most one KV write per this many seconds mid-run
# (finalize always publishes).
_PUBLISH_INTERVAL_S = 0.5

KV_PREFIX = b"train::"


def report_key(gang: str) -> bytes:
    return KV_PREFIX + gang.encode()


class GoodputLedger:
    """One per fit(); survives gang restarts (recover is a bucket, not a new
    ledger). Driver-thread only."""

    def __init__(self, gang: str, world_size: int):
        self.gang = gang
        self.world_size = world_size
        self._wall_t0 = time.perf_counter()
        self._mark = self._wall_t0
        self.buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.steps = 0
        self.failures = 0
        # Elastic membership changes (not failures): count + last transition.
        self.resizes = 0
        self.last_resize: Optional[Dict[str, Any]] = None
        self.proactive_checkpoints = 0
        self.status = "running"
        self.max_skew_s = 0.0
        self.last_skew_s = 0.0
        self.per_rank: Dict[str, Dict[str, Any]] = {}
        # Straggler naming is modal, not max: the rank that is slowest in the
        # most rounds. A single noisy round (gang bring-up stagger inflates
        # everyone's first step differently) must not name the straggler for
        # the whole run.
        self._slow_rounds: Dict[int, int] = {}
        self._slow_last: Dict[int, Dict[str, Any]] = {}
        self._last_publish = 0.0

    # ------------------------------------------------------------- intervals
    def _take(self) -> float:
        now = time.perf_counter()
        dt = max(0.0, now - self._mark)
        self._mark = now
        return dt

    def account(self, bucket: str) -> float:
        """Classify everything since the last mark into one bucket."""
        dt = self._take()
        self.buckets[bucket] += dt
        return dt

    def account_init(self, rendezvous_s: float) -> None:
        """First bring-up window: the gang-join blocking the workers measured
        is rendezvous_wait; the rest (PG, actor spawn, backend) is init."""
        dt = self._take()
        r = min(max(0.0, rendezvous_s), dt)
        self.buckets["rendezvous_wait"] += r
        self.buckets["init"] += dt - r
        self.publish()

    def fold_round(self, telems: List[Dict[str, Any]]) -> None:
        """Classify one result round from the gang's per-step telemetry dicts
        (one per rank; may be empty when observability is off)."""
        dt = self._take()
        if not telems:
            self.buckets["idle"] += dt
            return
        self.steps += 1
        n = len(telems)
        means: Dict[str, float] = {}
        for t in telems:
            for p, v in (t.get("phases") or {}).items():
                means[p] = means.get(p, 0.0) + v / n
        total = sum(means.values())
        # Worker clocks can drift past the driver-observed interval; scale
        # down so the round never claims more wall time than it occupied.
        scale = min(1.0, dt / total) if total > 0.0 else 0.0
        for p, v in means.items():
            self.buckets[_PHASE_BUCKET.get(p, "idle")] += v * scale
        self.buckets["idle"] += dt - total * scale
        self.publish()

    def note_skew(self, skew_s: float, straggler: Optional[Dict[str, Any]],
                  per_rank: Dict[str, Dict[str, Any]]) -> None:
        self.last_skew_s = skew_s
        self.max_skew_s = max(self.max_skew_s, skew_s)
        if straggler is not None:
            rank = straggler["rank"]
            self._slow_rounds[rank] = self._slow_rounds.get(rank, 0) + 1
            self._slow_last[rank] = straggler
        self.per_rank = per_rank

    def note_resize(self, old_world: int, new_world: int, reason: str,
                    resize_s: float, ckpt_source: str) -> None:
        """Record one elastic membership change; the wall time was already
        accounted into the resize bucket by the trainer."""
        self.resizes += 1
        self.world_size = new_world
        self.last_resize = {
            "old_world": old_world,
            "new_world": new_world,
            "direction": "grow" if new_world > old_world else "shrink",
            "reason": reason,
            "resize_s": round(resize_s, 6),
            "ckpt_source": ckpt_source,
        }
        self.publish(force=True)

    @property
    def straggler(self) -> Optional[Dict[str, Any]]:
        """The modal slow rank with its latest round's phase attribution,
        plus how many rounds it was the slowest."""
        if not self._slow_rounds:
            return None
        rank = max(self._slow_rounds, key=self._slow_rounds.get)
        out = dict(self._slow_last[rank])
        out["slow_rounds"] = self._slow_rounds[rank]
        out["rounds"] = sum(self._slow_rounds.values())
        return out

    # --------------------------------------------------------------- report
    def wall_s(self) -> float:
        return time.perf_counter() - self._wall_t0

    def report(self) -> Dict[str, Any]:
        wall = self.wall_s()
        accounted = sum(self.buckets.values())
        return {
            "gang": self.gang,
            "world_size": self.world_size,
            "status": self.status,
            "updated_at": time.time(),
            "wall_s": round(wall, 6),
            "buckets": {b: round(v, 6) for b, v in self.buckets.items()},
            "coverage": round(accounted / wall, 4) if wall > 0 else 1.0,
            "goodput_frac": round(self.buckets["productive"] / wall, 4)
            if wall > 0 else 0.0,
            "steps": self.steps,
            "failures": self.failures,
            "resizes": self.resizes,
            "last_resize": self.last_resize,
            "proactive_checkpoints": self.proactive_checkpoints,
            "skew_s": round(self.last_skew_s, 6),
            "max_skew_s": round(self.max_skew_s, 6),
            "straggler": self.straggler,
            "per_rank": self.per_rank,
        }

    def publish(self, force: bool = False) -> None:
        """Best-effort KV write of the current report (throttled mid-run).
        Gated on the observability knob; never raises."""
        try:
            from ray_tpu._private.telemetry import obs_enabled

            if not obs_enabled():
                return
            now = time.monotonic()
            if not force and now - self._last_publish < _PUBLISH_INTERVAL_S:
                return
            self._last_publish = now
            from ray_tpu._private.worker import global_worker

            ctx = global_worker.context
            if ctx is None:
                return
            ctx.kv("put", report_key(self.gang),
                   json.dumps(self.report()).encode())
        except Exception:  # noqa: BLE001 — shutdown races, head gone
            pass

    def finalize(self, status: str) -> Dict[str, Any]:
        """Sweep the tail into idle, stamp final status, publish."""
        self.account("idle")
        self.status = status
        self.publish(force=True)
        return self.report()
