"""The Backend plugin seam: per-framework gang setup/teardown hooks.

Reference: `python/ray/train/backend.py:53` (`Backend`) and `BackendConfig`.
A `BackendConfig` names its `Backend` class; the `BackendExecutor` invokes the
hooks around worker-group lifecycle. The JAX backend lives in
`ray_tpu/train/jax/config.py`.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Framework hooks; default implementation is a no-op gang."""

    share_cwd: bool = False

    def on_start(self, worker_group, backend_config: BackendConfig) -> None:
        """After the worker gang is up, before any training starts."""

    def on_training_start(self, worker_group, backend_config: BackendConfig) -> None:
        """Right before the user training function launches."""

    def on_shutdown(self, worker_group, backend_config: BackendConfig) -> None:
        """Before the worker gang is torn down."""
