"""JaxTrainer: DataParallelTrainer with the JAX SPMD backend.

The analogue of `python/ray/train/torch/torch_trainer.py` (`TorchTrainer`),
re-designed TPU-first: `ScalingConfig(num_workers=H, use_tpu=True,
mesh={"data": D, "tensor": T, ...})` gang-places one worker per TPU host,
joins them into one multi-controller program, and hands the user loop a global
`jax.sharding.Mesh` via `ray_tpu.air.session.get_mesh()`.

Example:

    def train_loop(config):
        mesh = session.get_mesh()
        state = create_train_state(cfg, key, opt, mesh=mesh)
        step = make_train_step(cfg, opt, mesh=mesh)
        for batch in data:
            state, metrics = step(state, shard_batch(batch, mesh))
            session.report({"loss": float(metrics["loss"])})

    trainer = JaxTrainer(
        train_loop, scaling_config=ScalingConfig(num_workers=4, use_tpu=True)
    )
    result = trainer.fit()
"""

from __future__ import annotations

from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax.config import JaxConfig


class JaxTrainer(DataParallelTrainer):
    _default_backend_config = JaxConfig
