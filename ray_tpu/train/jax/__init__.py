"""JAX/TPU training backend — the flagship Train integration.

The analogue of `python/ray/train/torch/` (`torch/config.py:29,69,113,155`):
where `_TorchBackend.on_start` runs `dist.init_process_group` on every worker,
`_JaxBackend.on_start` runs `jax.distributed.initialize` — after which the
worker gang is ONE multi-controller SPMD program: `jax.devices()` is global,
and the mesh built from `ScalingConfig.mesh` spans every TPU chip of the gang,
with collectives riding ICI inside the user's jitted step.
"""

from ray_tpu.train.jax.config import JaxConfig, _JaxBackend
from ray_tpu.train.jax.jax_trainer import JaxTrainer
from ray_tpu.air import session as _session

get_mesh = _session.get_mesh

__all__ = ["JaxConfig", "JaxTrainer", "get_mesh"]
