"""Rule-based checkpoint resharding: regex rules -> PartitionSpec pytree.

The machinery behind elastic resize (ISSUE 19): when a gang shrinks or grows,
the surviving checkpoint shards must re-partition onto the new mesh. The rule
format follows the flax/EasyLM `match_partition_rules` idiom (SNIPPETS.md [2]):
an ordered list of ``(regex, PartitionSpec)`` pairs matched against the
'/'-joined path of each leaf; first match wins; scalars are always replicated.

Everything here is host-side and jax-optional: partition specs are plain
tuples (``None`` = replicated axis, an axis *name* marks the sharded
dimension), shards are numpy arrays, and `device_put_tree` upgrades the result
to `jax.NamedSharding` only when jax and a live mesh are available. That keeps
the elastic path testable on hosts whose jaxlib cannot run multiprocess SPMD.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# A partition spec is a tuple with one entry per array dimension: None keeps
# the dimension replicated, a string names the mesh axis it shards over. The
# empty tuple replicates the whole leaf (always used for scalars).
PartitionSpec = Tuple[Optional[str], ...]

REPLICATED: PartitionSpec = ()


def tree_paths(tree: Any, sep: str = "/") -> Dict[str, Any]:
    """Flatten a nested dict/list pytree into {joined_path: leaf}."""
    out: Dict[str, Any] = {}

    def walk(node: Any, prefix: List[str]) -> None:
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], prefix + [str(k)])
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, prefix + [str(i)])
        else:
            out[sep.join(prefix)] = node

    walk(tree, [])
    return out


def tree_unflatten(paths: Dict[str, Any], sep: str = "/") -> Any:
    """Inverse of `tree_paths` for dict-shaped trees (lists come back as
    dicts keyed by index — fine for checkpoint state, which is dict-shaped)."""
    root: Dict[str, Any] = {}
    for path, leaf in paths.items():
        parts = path.split(sep) if path else [""]
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


def match_partition_rules(
    rules: Sequence[Tuple[str, PartitionSpec]], tree: Any, sep: str = "/"
) -> Dict[str, PartitionSpec]:
    """Map every leaf path to its PartitionSpec via the first matching regex.

    Scalars (0-d) are replicated without consulting the rules. A non-scalar
    leaf matching no rule is an error — silent replication of a sharded
    tensor is how resharding corrupts a run.
    """
    specs: Dict[str, PartitionSpec] = {}
    for path, leaf in tree_paths(tree, sep).items():
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0:
            specs[path] = REPLICATED
            continue
        for pattern, spec in rules:
            if re.search(pattern, path) is not None:
                specs[path] = tuple(spec)
                break
        else:
            raise ValueError(
                f"no partition rule matches checkpoint leaf '{path}' "
                f"(shape {tuple(shape)}); add a rule or an explicit "
                f"catch-all ('.*', ())"
            )
    return specs


def _shard_axis(spec: PartitionSpec) -> Optional[int]:
    """The (single) dimension a spec shards, or None if fully replicated."""
    axes = [i for i, a in enumerate(spec) if a is not None]
    if not axes:
        return None
    if len(axes) > 1:
        raise ValueError(f"at most one sharded dimension supported, got {spec}")
    return axes[0]


def shard_for_rank(
    tree: Any,
    rules: Sequence[Tuple[str, PartitionSpec]],
    world_size: int,
    rank: int,
    sep: str = "/",
) -> Any:
    """Slice a full (host) state tree down to one rank's shard.

    Sharded dimensions use balanced uneven splits (np.array_split semantics:
    shard sizes differ by at most one) so ANY world size can host the state —
    the point of elastic resize is that 4 -> 3 must work without padding the
    model to a magic multiple.
    """
    specs = match_partition_rules(rules, tree, sep)
    leaves = tree_paths(tree, sep)
    out: Dict[str, Any] = {}
    for path, leaf in leaves.items():
        axis = _shard_axis(specs[path])
        if axis is None:
            out[path] = leaf
            continue
        arr = np.asarray(leaf)
        start, stop = shard_bounds(arr.shape[axis], world_size, rank)
        index = [slice(None)] * arr.ndim
        index[axis] = slice(start, stop)
        out[path] = np.ascontiguousarray(arr[tuple(index)])
    return tree_unflatten(out, sep)


def shard_bounds(dim: int, world_size: int, rank: int) -> Tuple[int, int]:
    """[start, stop) of `rank`'s slice of a dimension of size `dim` under the
    balanced uneven split (first dim % world ranks get the extra element)."""
    base, extra = divmod(dim, world_size)
    start = rank * base + min(rank, extra)
    return start, start + base + (1 if rank < extra else 0)


def gather_tree(
    shards_by_rank: Dict[int, Any],
    rules: Sequence[Tuple[str, PartitionSpec]],
    sep: str = "/",
) -> Any:
    """Reassemble the full state tree from one shard per rank (the inverse of
    `shard_for_rank` at the world size == len(shards_by_rank) that cut them).

    Replicated leaves are taken from the lowest rank; sharded leaves are
    concatenated along their partition axis in rank order.
    """
    if not shards_by_rank:
        raise ValueError("gather_tree needs at least one shard")
    ranks = sorted(shards_by_rank)
    flat = {rk: tree_paths(shards_by_rank[rk], sep) for rk in ranks}
    template = flat[ranks[0]]
    specs = match_partition_rules(rules, shards_by_rank[ranks[0]], sep)
    out: Dict[str, Any] = {}
    for path, leaf in template.items():
        axis = _shard_axis(specs[path])
        if axis is None:
            out[path] = leaf
            continue
        out[path] = np.concatenate(
            [np.asarray(flat[rk][path]) for rk in ranks], axis=axis
        )
    return tree_unflatten(out, sep)


def reshard(
    tree: Any,
    rules: Sequence[Tuple[str, PartitionSpec]],
    new_world_size: int,
    new_rank: int,
    sep: str = "/",
) -> Any:
    """One-step repartition of a full tree onto a resized gang: what a
    surviving/new rank calls on the recovered checkpoint at resume."""
    return shard_for_rank(tree, rules, new_world_size, new_rank, sep)


def device_put_tree(tree: Any, rules, mesh=None, sep: str = "/") -> Any:
    """Best effort: place a host tree onto jax devices with NamedSharding
    derived from the rules. Falls back to the host tree when jax (or a mesh)
    is unavailable, so callers can use it unconditionally."""
    if mesh is None:
        return tree
    try:
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as JaxSpec
    except Exception:  # noqa: BLE001 — jax not importable on this host
        return tree
    specs = match_partition_rules(rules, tree, sep)
    leaves = tree_paths(tree, sep)
    out = {}
    for path, leaf in leaves.items():
        try:
            sharding = NamedSharding(mesh, JaxSpec(*specs[path]))
            out[path] = jax.device_put(np.asarray(leaf), sharding)
        except Exception:  # noqa: BLE001 — axis not in mesh, CPU-only host
            out[path] = leaf
    return tree_unflatten(out, sep)


def resume_state(ckpt_dict: Dict[str, Any]) -> Tuple[int, Any, Any]:
    """Unpack a recovery checkpoint assembled by the elastic controller:
    returns (step, full state tree, rules). Raises KeyError on a checkpoint
    that is not elastic-shaped, so callers can fall back to their own format.
    """
    return ckpt_dict["elastic_step"], ckpt_dict["state"], ckpt_dict["rules"]
