"""JaxConfig/_JaxBackend: gang-wide jax.distributed bring-up + mesh plumbing.

Reference seam: `python/ray/train/torch/config.py` — `_TorchBackend.on_start`
(`:155`) runs `_setup_torch_process_group` (`:69`) with rank 0 as master. Here
rank 0's host:port becomes the jax coordinator; every worker enters
`jax.distributed.initialize(coordinator, num_processes, process_id)`
concurrently (it blocks until the full gang joins — the same all-or-nothing
gang semantics, SURVEY.md §7).

After on_start, each worker's `jax.devices()` spans the whole gang. The mesh
builder (run inside the session thread) reshapes the global device list into
the `ScalingConfig.mesh` axes (`MeshSpec`, axis order tensor-innermost so TP
collectives ride the fastest ICI links).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig


def _init_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    if num_processes <= 1:
        return len(jax.devices())
    # initialize() blocks until every process joins — a gang rendezvous.
    # Account the blocked time so the goodput ledger's rendezvous_wait bucket
    # covers jax bring-up, not just the collective KV waits.
    import time

    from ray_tpu.util.collective import rendezvous

    t0 = time.perf_counter()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    finally:
        rendezvous.note_wait(time.perf_counter() - t0)
    return len(jax.devices())


def _shutdown_jax_distributed():
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


def _build_mesh(mesh_axes: Optional[Dict[str, int]]):
    """Session-thread mesh builder: global devices -> jax.sharding.Mesh."""
    import jax

    from ray_tpu.parallel import MeshSpec

    devices = jax.devices()
    if mesh_axes:
        spec = MeshSpec.from_dict(mesh_axes)
        if spec.num_devices != len(devices):
            raise ValueError(
                f"ScalingConfig.mesh {mesh_axes} wants {spec.num_devices} devices "
                f"but the gang has {len(devices)}"
            )
    else:
        spec = MeshSpec.for_data_parallel(len(devices))
    return spec.build(devices)


@dataclass
class JaxConfig(BackendConfig):
    """Backend config for JAX SPMD training.

    distributed: force multi-controller bring-up on/off (default: automatic —
      on iff the gang has more than one worker).
    """

    distributed: Optional[bool] = None

    @property
    def backend_cls(self):
        return _JaxBackend

    def mesh_builder(self, scaling_config: ScalingConfig) -> Callable:
        spec = scaling_config.mesh_spec()
        axes = None
        if spec is not None:
            from ray_tpu.parallel import AXIS_ORDER

            axes = {a: s for a, s in zip(AXIS_ORDER, spec.shape) if s > 1}
        return functools.partial(_build_mesh, axes)


class _JaxBackend(Backend):
    def on_start(self, executor, backend_config: JaxConfig):
        wg = executor.worker_group
        n = len(wg)
        distributed = (
            backend_config.distributed
            if backend_config.distributed is not None
            else n > 1
        )
        if not distributed:
            return
        # Rank 0's node hosts the jax coordination service.
        rank_of = executor.ranks
        rank0_index = rank_of.index(0)
        meta = wg._metadata or wg.fetch_metadata()
        port = wg.execute_single(rank0_index, _free_port_fn)
        coordinator = f"{meta[rank0_index].node_ip}:{port}"
        # All workers must enter initialize() together: fire async, then gather.
        refs = []
        for i, w in enumerate(wg.workers):
            refs.append(
                w.execute.remote(_init_jax_distributed, coordinator, n, rank_of[i])
            )
        device_counts = ray_tpu.get(refs)
        if len(set(device_counts)) != 1:
            raise RuntimeError(
                f"workers disagree on global device count: {device_counts}"
            )

    def on_shutdown(self, executor, backend_config: JaxConfig):
        if executor.worker_group is not None:
            try:
                executor.worker_group.execute(_shutdown_jax_distributed)
            except Exception:
                pass


def _free_port_fn() -> int:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port
