"""LightGBMTrainer / LightGBMPredictor.

Reference: `python/ray/train/lightgbm/lightgbm_trainer.py`. Same engine as
XGBoostTrainer (`ray_tpu/train/gbdt/_engine.py`) with lightgbm param names
translated (learning_rate, num_iterations, lambda_l2, min_gain_to_split,
min_sum_hessian_in_leaf, objective regression/binary).
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.train.gbdt_trainer import GBDTTrainer
from ray_tpu.train.xgboost import XGBoostPredictor

_OBJECTIVES = {
    "regression": "reg:squarederror",
    "regression_l2": "reg:squarederror",
    "binary": "binary:logistic",
}


class LightGBMTrainer(GBDTTrainer):
    def _translate_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(params)
        if "objective" in out:
            out["objective"] = _OBJECTIVES.get(out["objective"], out["objective"])
        for src, dst in [
            ("learning_rate", "eta"),
            ("num_iterations", "num_boost_round"),
            ("n_estimators", "num_boost_round"),
            ("lambda_l2", "reg_lambda"),
            ("min_gain_to_split", "gamma"),
            ("min_sum_hessian_in_leaf", "min_child_weight"),
        ]:
            if src in out:
                out[dst] = out.pop(src)
        return out


class LightGBMPredictor(XGBoostPredictor):
    pass


__all__ = ["LightGBMTrainer", "LightGBMPredictor"]
