"""BaseTrainer: configs + the fit() contract.

Reference: `python/ray/train/base_trainer.py` (`BaseTrainer.fit:557`). In the
reference every fit routes through Tune as a single trial; here `fit()` runs
the training loop directly and `as_trainable()` exposes the same loop to
`ray_tpu.tune.Tuner` for sweeps (same seam, inverted layering — Tune drives
Train when asked rather than always sitting between).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


class TrainingFailedError(RuntimeError):
    """Training did not finish within the FailureConfig retry budget."""


def default_storage_path() -> str:
    return os.environ.get(
        "RAY_TPU_RESULTS_DIR", os.path.expanduser("~/ray_tpu_results")
    )


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.metadata = metadata or {}

    # Implemented by subclasses: run the whole training job, return a Result.
    def _fit_impl(self, trial_info: Optional[Dict[str, str]] = None) -> Result:
        raise NotImplementedError

    def fit(self) -> Result:
        result = self._fit_impl()
        if result.error is not None:
            raise TrainingFailedError(str(result.error)) from result.error
        return result

    def run_dir(self) -> str:
        name = self.run_config.name or f"{type(self).__name__}_{int(time.time())}"
        # Cache: a trainer maps to exactly one run directory across restarts.
        if self.run_config.name is None:
            self.run_config.name = name
        base = self.run_config.storage_path or default_storage_path()
        return os.path.join(os.path.expanduser(base), name)

    def as_trainable(self):
        """A Tune function-trainable wrapping this trainer (param_space's
        'train_loop_config' key overrides the trainer's loop config per trial)."""
        trainer = self

        def _trainable(config: Dict[str, Any]):
            import copy

            t = copy.copy(trainer)
            if "train_loop_config" in config and hasattr(t, "_train_loop_config"):
                merged = dict(getattr(t, "_train_loop_config") or {})
                merged.update(config["train_loop_config"])
                t._train_loop_config = merged
            from ray_tpu.air import session

            t._inside_tune = True
            result = t._fit_impl(
                trial_info={
                    "trial_name": session.get_trial_name(),
                    "trial_id": session.get_trial_id(),
                    "trial_dir": session.get_trial_dir(),
                    "experiment_name": session.get_experiment_name(),
                }
            )
            if result.error is not None:
                raise result.error

        _trainable.__name__ = type(self).__name__
        return _trainable
