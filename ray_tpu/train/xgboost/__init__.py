"""XGBoostTrainer / XGBoostPredictor.

Reference: `python/ray/train/xgboost/xgboost_trainer.py` (+
`xgboost_predictor.py`): distributed `hist` boosting over Dataset shards and
checkpoint-based batch prediction. The tree engine is the in-repo numpy
histogram implementation (`ray_tpu/train/gbdt/_engine.py`) with xgboost's
param names and split math — xgboost itself is not vendored on TPU hosts;
the distribution strategy (global quantile sketch + per-level histogram
allreduce) is identical, so params and results transfer.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.gbdt._engine import GBDTModel
from ray_tpu.train.gbdt_trainer import MODEL_KEY, GBDTTrainer


class XGBoostTrainer(GBDTTrainer):
    """`params` uses xgboost names: objective ("reg:squarederror" |
    "binary:logistic"), eta/learning_rate, max_depth, reg_lambda, gamma,
    min_child_weight, max_bin, base_score, num_boost_round."""


class XGBoostPredictor:
    """Batch predictor over a fitted checkpoint (reference:
    `xgboost_predictor.py`): usable directly or as a class UDF in
    `Dataset.map_batches(XGBoostPredictor, fn_constructor_args=(ckpt,),
    compute="actors")` for distributed batch inference."""

    def __init__(self, checkpoint: Checkpoint):
        model = checkpoint.to_dict().get(MODEL_KEY)
        if not isinstance(model, GBDTModel):
            raise ValueError("checkpoint does not contain a GBDT model")
        self.model = model

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint) -> "XGBoostPredictor":
        return cls(checkpoint)

    def predict(self, batch: Dict[str, Any]) -> Dict[str, np.ndarray]:
        X = np.stack(
            [np.asarray(batch[c]) for c in self.model.feature_columns], axis=1
        )
        return {"predictions": self.model.predict(X)}

    # map_batches class-UDF protocol.
    def __call__(self, batch: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return self.predict(batch)


__all__ = ["XGBoostTrainer", "XGBoostPredictor"]
