"""DataParallelTrainer: SPMD training over a gang of worker actors.

Reference: `python/ray/train/data_parallel_trainer.py:56` +
`training_loop:385`. The driver loop consumes per-round results from the gang
(`BackendExecutor.get_next_results`), persists rank-0 checkpoints, and
restarts the whole gang from the last checkpoint on worker failure
(`FailureConfig.max_failures`, `air/config.py:512`) — gang restarts are
all-or-nothing because a jax multi-controller program cannot resize
(SURVEY.md §7 "SPMD gang semantics").
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.air import session as air_session
from ray_tpu.train._internal.backend_executor import (
    BackendExecutor,
    GangResizeNeeded,
    TrainingWorkerError,
)
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager
from ray_tpu.train._internal.ledger import GoodputLedger
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer

# Distinguishes concurrent/successive fits from one driver when there is no
# Tune trial id to serve as the gang id.
_GANG_SEQ = itertools.count()


class DataParallelTrainer(BaseTrainer):
    _default_backend_config: Callable[[], BackendConfig] = BackendConfig

    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict[str, Any]], None],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        from ray_tpu._private import usage

        usage.record_library_usage("train")
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
            metadata=metadata,
        )
        if not callable(train_loop_per_worker):
            raise TypeError("train_loop_per_worker must be callable")
        self._train_fn = train_loop_per_worker
        self._train_loop_config = dict(train_loop_config or {})
        self.backend_config = backend_config or type(self)._default_backend_config()
        self._inside_tune = False

    # ------------------------------------------------------------- data ingest
    def _dataset_shards(
        self, num_workers: Optional[int] = None
    ) -> Optional[List[Dict[str, Any]]]:
        """Pipelined per-worker iterators over each provided dataset (Data
        P18 ingest seam; reference: `streaming_split` feeding
        `session.get_dataset_shard`, `python/ray/data/dataset.py:1134`).

        ray_tpu.data Datasets become `DataIterator`s over ONE shared
        executing stream — blocks are produced DURING training and assigned
        to workers on demand, so epoch ingest overlaps the train loop and
        nothing materializes up front. Anything else is replicated to every
        worker.
        """
        if not self.datasets:
            return None
        n = num_workers or self.scaling_config.num_workers
        shards: List[Dict[str, Any]] = [{} for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                parts = ds.streaming_split(n, equal=True)
                for i in range(n):
                    shards[i][name] = parts[i]
            else:
                for i in range(n):
                    shards[i][name] = ds
        return shards

    # ------------------------------------------------------------- elastic path
    def _resize_and_resume(
        self,
        executor: BackendExecutor,
        reason: str,
        grow: bool,
        ledger,
        gang_id: str,
        ckpt_mgr: CheckpointManager,
        latest_ckpt: Optional[Checkpoint],
        mesh_builder,
    ) -> Optional[Checkpoint]:
        """Re-form an elastic gang in place and restart its sessions from the
        newest checkpoint. Returns the checkpoint resumed from; raises
        TrainingWorkerError (budgeted, whole-gang restart) when the gang
        cannot re-form at min_workers."""
        info = executor.resize_gang(reason, grow=grow)
        resume_ckpt = (
            info["checkpoint"] or ckpt_mgr.latest_checkpoint or latest_ckpt
        )
        executor.start_training(
            self._train_fn,
            self._train_loop_config,
            checkpoint=resume_ckpt,
            dataset_shards=self._dataset_shards(info["new_world"]),
            mesh_builder=mesh_builder,
        )
        # Everything since the last round fold — detection, drain, respawn,
        # re-rendezvous, session re-init — is the resize badput window; its
        # length is the per-event time-to-recover.
        resize_s = ledger.account("resize") if ledger is not None else 0.0
        direction = "grow" if info["new_world"] > info["old_world"] else "shrink"
        from ray_tpu._private.events import emit_event
        from ray_tpu._private.telemetry import metrics_enabled, train_metrics

        emit_event(
            "train_gang_resize",
            f"gang {gang_id}: re-formed {info['old_world']} -> "
            f"{info['new_world']} workers ({reason}; {resize_s:.2f}s, "
            f"resumed from {info['ckpt_source']} checkpoint)",
            severity="warning",
            source="train-driver",
            gang=gang_id,
            old_world=info["old_world"],
            new_world=info["new_world"],
            direction=direction,
            reason=reason,
            resize_s=round(resize_s, 6),
            ckpt_source=info["ckpt_source"],
            step=info["recovered_step"],
        )
        if metrics_enabled():
            train_metrics()["resize_total"].inc(
                1, {"gang": gang_id, "direction": direction}
            )
        if ledger is not None:
            ledger.note_resize(
                info["old_world"], info["new_world"], reason, resize_s,
                info["ckpt_source"],
            )
        return resume_ckpt

    # ---------------------------------------------------------------- fit loop
    def _fit_impl(self, trial_info: Optional[Dict[str, str]] = None) -> Result:
        # Inside a Tune sweep each trial must checkpoint into its own trial
        # directory, never the shared trainer run_dir (concurrent trials would
        # overwrite/prune each other's checkpoint_NNNNNN entries).
        run_dir = (trial_info or {}).get("trial_dir") or self.run_dir()
        ckpt_mgr = CheckpointManager(run_dir, self.run_config.checkpoint_config)
        max_failures = self.run_config.failure_config.max_failures
        latest_ckpt = self.resume_from_checkpoint
        last_metrics: Optional[Dict[str, Any]] = None
        failures = 0
        tune_session = air_session._get_session() if self._inside_tune else None

        mesh_builder = None
        if hasattr(self.backend_config, "mesh_builder"):
            mesh_builder = self.backend_config.mesh_builder(self.scaling_config)

        # One gang id (and one goodput ledger) per fit: restarts keep both so
        # recovery shows up as badput of the same run, not a fresh ledger.
        gang_id = (trial_info or {}).get("trial_id") or (
            f"train-{os.getpid()}-{next(_GANG_SEQ)}"
        )
        from ray_tpu._private.telemetry import metrics_enabled

        ledger = (
            GoodputLedger(gang_id, self.scaling_config.num_workers)
            if metrics_enabled()
            else None
        )

        while True:
            executor = BackendExecutor(
                self.backend_config, self.scaling_config, trial_info,
                gang_id=gang_id, ledger=ledger,
            )
            try:
                recovering = failures > 0
                executor.start()
                executor.start_training(
                    self._train_fn,
                    self._train_loop_config,
                    checkpoint=latest_ckpt,
                    dataset_shards=self._dataset_shards(),
                    mesh_builder=mesh_builder,
                )
                if ledger is not None:
                    if recovering:
                        # Detection + full gang restart: all recover badput.
                        recover_s = ledger.account("recover")
                        from ray_tpu._private.events import emit_event

                        emit_event(
                            "train_gang_recover",
                            f"gang {gang_id}: restarted after worker failure "
                            f"#{failures} ({recover_s:.2f}s to recover)",
                            severity="warning",
                            source="train-driver",
                            gang=gang_id,
                            failures=failures,
                            recover_s=round(recover_s, 6),
                        )
                    else:
                        ledger.account_init(executor.gang_rendezvous_seconds())
                while True:
                    try:
                        results = executor.get_next_results()
                    except GangResizeNeeded as sig:
                        # Elastic membership change: re-form in place, resume
                        # from the newest checkpoint (in-memory replica when
                        # it beats the last disk persist). NOT a failure.
                        latest_ckpt = self._resize_and_resume(
                            executor, sig.reason, sig.grow, ledger, gang_id,
                            ckpt_mgr, latest_ckpt, mesh_builder,
                        )
                        continue
                    if results is None:
                        break
                    rank0 = results[0]
                    last_metrics = rank0.metrics
                    ckpt = next(
                        (r.checkpoint for r in results if r.checkpoint is not None),
                        None,
                    )
                    if ckpt is not None:
                        latest_ckpt = ckpt_mgr.register(ckpt, rank0.metrics)
                        executor.note_persisted_checkpoint()
                        if ledger is not None:
                            # Driver-side persist rides the checkpoint bucket.
                            ledger.account("checkpoint")
                    if tune_session is not None:
                        # Forward to Tune so schedulers/search see every report.
                        tune_session.report(
                            dict(last_metrics or {}),
                            checkpoint=ckpt if ckpt is not None else None,
                        )
                    if executor.should_grow():
                        # Capacity returned: re-expand toward the target.
                        latest_ckpt = self._resize_and_resume(
                            executor, "capacity returned", True, ledger,
                            gang_id, ckpt_mgr, latest_ckpt, mesh_builder,
                        )
                executor.shutdown()
                if ledger is not None:
                    ledger.finalize("done")
                return Result(
                    metrics=last_metrics,
                    checkpoint=ckpt_mgr.best_checkpoint(),
                    error=None,
                    path=run_dir,
                    best_checkpoints=ckpt_mgr.best_checkpoints(),
                )
            except TrainingWorkerError as e:
                executor.shutdown()
                failures += 1
                if ledger is not None:
                    ledger.failures = failures
                if max_failures >= 0 and failures > max_failures:
                    if ledger is not None:
                        ledger.finalize("failed")
                    return Result(
                        metrics=last_metrics,
                        checkpoint=ckpt_mgr.best_checkpoint(),
                        error=e,
                        path=run_dir,
                    )
                # Retry the whole gang from the most recent checkpoint.
                latest_ckpt = ckpt_mgr.latest_checkpoint or latest_ckpt
            except BaseException as e:  # driver-side bug: no retry
                executor.shutdown()
                if ledger is not None:
                    ledger.finalize("failed")
                if not isinstance(e, Exception):
                    raise  # KeyboardInterrupt/SystemExit must propagate
                return Result(
                    metrics=last_metrics,
                    checkpoint=ckpt_mgr.best_checkpoint(),
                    error=e if isinstance(e, Exception) else RuntimeError(str(e)),
                    path=run_dir,
                )
