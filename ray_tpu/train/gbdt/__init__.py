"""Distributed histogram-GBDT engine (see `_engine.py`)."""

from ray_tpu.train.gbdt._engine import GBDTModel, Tree

__all__ = ["GBDTModel", "Tree"]
