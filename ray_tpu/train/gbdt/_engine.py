"""Distributed histogram gradient boosting: the numpy engine.

The distribution strategy is the reference's GBDT path
(`/root/reference/python/ray/train/gbdt_trainer.py:105` driving xgboost-ray's
`hist` tree method): each worker holds a data shard, bins features against
GLOBAL quantile cut points, and per tree LEVEL computes gradient/hessian
histograms that are summed across workers (the allreduce xgboost performs via
rabit); the driver finds splits on the aggregated histograms, so the fitted
model is IDENTICAL to single-node training on the concatenated data.

xgboost/lightgbm are not vendored on TPU hosts, so the math lives here in
~300 lines of numpy: exact second-order split gain, reg_lambda/gamma/
min_child_weight regularization, level-wise growth.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass
class Tree:
    """One regression tree in flat arrays (leaf: feature == -1)."""

    feature: np.ndarray  # int32 [n_nodes]
    threshold: np.ndarray  # float64 [n_nodes] raw cut value (x <= t -> left)
    left: np.ndarray  # int32 [n_nodes]
    right: np.ndarray  # int32 [n_nodes]
    value: np.ndarray  # float64 [n_nodes] leaf weight

    def predict(self, X: np.ndarray) -> np.ndarray:
        node = np.zeros(len(X), dtype=np.int32)
        # Level-wise vectorized descent: at most n_nodes iterations.
        for _ in range(len(self.feature)):
            internal = self.feature[node] >= 0
            if not internal.any():
                break
            idx = np.nonzero(internal)[0]
            n = node[idx]
            go_left = X[idx, self.feature[n]] <= self.threshold[n]
            node[idx] = np.where(go_left, self.left[n], self.right[n])
        return self.value[node]


@dataclass
class GBDTModel:
    """Boosted ensemble + the metadata needed for standalone prediction."""

    trees: List[Tree] = field(default_factory=list)
    base_score: float = 0.5
    objective: str = "reg:squarederror"
    learning_rate: float = 0.3
    feature_columns: List[str] = field(default_factory=list)
    label_column: str = ""

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        out = np.full(len(X), self.base_score, dtype=np.float64)
        for t in self.trees:
            out += self.learning_rate * t.predict(X)
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        margin = self.predict_margin(np.asarray(X, dtype=np.float64))
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-margin))
        return margin


DEFAULT_PARAMS = {
    "objective": "reg:squarederror",
    "eta": 0.3,
    "max_depth": 6,
    "reg_lambda": 1.0,
    "gamma": 0.0,
    "min_child_weight": 1.0,
    "max_bin": 256,
    "base_score": 0.5,
}


def grad_hess(margin: np.ndarray, y: np.ndarray, objective: str):
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + np.exp(-margin))
        return p - y, np.maximum(p * (1.0 - p), 1e-16)
    if objective == "reg:squarederror":
        return margin - y, np.ones_like(margin)
    raise ValueError(f"unsupported objective {objective!r}")


def loss_of(margin: np.ndarray, y: np.ndarray, objective: str) -> Tuple[float, str]:
    if objective == "binary:logistic":
        p = np.clip(1.0 / (1.0 + np.exp(-margin)), 1e-12, 1 - 1e-12)
        return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).sum()), "logloss"
    return float(((margin - y) ** 2).sum()), "rmse"


def make_bin_edges(sample: np.ndarray, max_bin: int) -> List[np.ndarray]:
    """Per-feature global quantile cut points from a row sample (the quantile
    sketch xgboost's `hist` builds; approximate, shared by every worker)."""
    edges = []
    qs = np.linspace(0, 1, max_bin + 1)[1:-1]
    for f in range(sample.shape[1]):
        col = sample[:, f]
        col = col[np.isfinite(col)]
        e = np.unique(np.quantile(col, qs)) if len(col) else np.array([0.0])
        edges.append(e.astype(np.float64))
    return edges


def bin_matrix(X: np.ndarray, edges: List[np.ndarray]) -> np.ndarray:
    out = np.empty(X.shape, dtype=np.uint16)
    for f, e in enumerate(edges):
        # bin b  <=>  x <= e[b] (b == len(e) is the overflow bin): split at
        # bin b sends x <= e[b] left, matching Tree.predict's `x <= t`.
        out[:, f] = np.searchsorted(e, X[:, f], side="left")
    return out


@dataclass
class _Split:
    node: int
    feature: int
    bin: int
    gain: float
    g_left: float
    h_left: float
    g_right: float
    h_right: float


def find_best_splits(
    G: np.ndarray,  # [n_active, F, B] summed over workers
    H: np.ndarray,
    active_nodes: List[int],
    params: Dict,
) -> Dict[int, Optional[_Split]]:
    """Exact best split per active node from aggregated histograms."""
    lam = params["reg_lambda"]
    gamma = params["gamma"]
    mcw = params["min_child_weight"]
    out: Dict[int, Optional[_Split]] = {}
    for k, node in enumerate(active_nodes):
        g_tot = G[k].sum(axis=1)[0] if G[k].size else 0.0  # same for every f
        h_tot = H[k].sum(axis=1)[0] if H[k].size else 0.0
        parent_score = g_tot * g_tot / (h_tot + lam)
        gl = np.cumsum(G[k], axis=1)  # [F, B] left sums at threshold b
        hl = np.cumsum(H[k], axis=1)
        gr = g_tot - gl
        hr = h_tot - hl
        gain = 0.5 * (
            gl * gl / (hl + lam) + gr * gr / (hr + lam) - parent_score
        ) - gamma
        ok = (hl >= mcw) & (hr >= mcw)
        # The last bin's "split" keeps everything left: never valid.
        ok[:, -1] = False
        gain = np.where(ok, gain, -np.inf)
        f, b = np.unravel_index(int(np.argmax(gain)), gain.shape)
        if not np.isfinite(gain[f, b]) or gain[f, b] <= 0:
            out[node] = None
            continue
        out[node] = _Split(
            node=node,
            feature=int(f),
            bin=int(b),
            gain=float(gain[f, b]),
            g_left=float(gl[f, b]),
            h_left=float(hl[f, b]),
            g_right=float(gr[f, b]),
            h_right=float(hr[f, b]),
        )
    return out


def leaf_value(g: float, h: float, lam: float) -> float:
    return -g / (h + lam)


class ShardState:
    """Per-worker training state over one data shard (runs inside an actor;
    pure numpy so it is also unit-testable inline)."""

    def __init__(self, X: np.ndarray, y: np.ndarray, params: Dict,
                 X_valid: Optional[np.ndarray] = None,
                 y_valid: Optional[np.ndarray] = None):
        self.X = np.asarray(X, dtype=np.float64)
        self.y = np.asarray(y, dtype=np.float64)
        self.params = params
        self.margin = np.full(len(self.y), params["base_score"], dtype=np.float64)
        self.X_valid = None if X_valid is None else np.asarray(X_valid, np.float64)
        self.y_valid = None if y_valid is None else np.asarray(y_valid, np.float64)
        self.valid_margin = (
            None
            if self.X_valid is None
            else np.full(len(self.y_valid), params["base_score"], dtype=np.float64)
        )
        self.binned: Optional[np.ndarray] = None
        self.n_bins = 0
        self.node_of: Optional[np.ndarray] = None
        self.g: Optional[np.ndarray] = None
        self.h: Optional[np.ndarray] = None

    def sample_rows(self, k: int, seed: int) -> np.ndarray:
        rng = np.random.default_rng(seed)
        if len(self.X) <= k:
            return self.X
        return self.X[rng.choice(len(self.X), size=k, replace=False)]

    def set_bins(self, edges: List[np.ndarray]) -> None:
        self.binned = bin_matrix(self.X, edges)
        self.n_bins = max(len(e) for e in edges) + 1

    def new_tree(self) -> None:
        self.node_of = np.zeros(len(self.y), dtype=np.int32)
        self.g, self.h = grad_hess(self.margin, self.y, self.params["objective"])

    def level_hist(self, active_nodes: List[int]):
        """G/H histograms [n_active, F, B] for this shard."""
        nA, F, B = len(active_nodes), self.X.shape[1], self.n_bins
        if len(self.y) == 0:
            return np.zeros((nA, F, B)), np.zeros((nA, F, B))
        slot = {n: k for k, n in enumerate(active_nodes)}
        s = np.array([slot.get(n, -1) for n in range(max(self.node_of.max() + 1, 1))])
        sample_slot = s[self.node_of]
        valid = sample_slot >= 0
        G = np.zeros((nA, F, B))
        H = np.zeros((nA, F, B))
        if valid.any():
            ss = sample_slot[valid]
            gv, hv = self.g[valid], self.h[valid]
            bv = self.binned[valid]
            for f in range(F):
                idx = ss * B + bv[:, f]
                G[:, f, :] = np.bincount(idx, weights=gv, minlength=nA * B).reshape(nA, B)
                H[:, f, :] = np.bincount(idx, weights=hv, minlength=nA * B).reshape(nA, B)
        return G, H

    def apply_splits(self, splits: List[Tuple[int, int, int, int, int]]) -> None:
        """splits: (node, feature, bin, left_id, right_id)."""
        for node, f, b, left_id, right_id in splits:
            mask = self.node_of == node
            go_left = self.binned[mask, f] <= b
            ids = np.where(go_left, left_id, right_id).astype(np.int32)
            self.node_of[mask] = ids

    def finalize_tree(self, tree: Tree, eta: float):
        """Apply the finished tree to train (via node assignment) and valid
        (via raw traversal) margins; return loss components. Without a live
        node assignment (fast-forwarding a resumed ensemble) the train side
        traverses raw features too."""
        if self.node_of is not None:
            self.margin += eta * tree.value[self.node_of]
        else:
            self.margin += eta * tree.predict(self.X)
        train_loss, metric = loss_of(self.margin, self.y, self.params["objective"])
        out = {"train_loss_sum": train_loss, "train_n": len(self.y), "metric": metric}
        if self.valid_margin is not None:
            self.valid_margin += eta * tree.predict(self.X_valid)
            vloss, _ = loss_of(self.valid_margin, self.y_valid, self.params["objective"])
            out["valid_loss_sum"] = vloss
            out["valid_n"] = len(self.y_valid)
        return out
