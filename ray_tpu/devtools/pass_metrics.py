"""Metrics discipline.

  M1 bad-name        a Counter/Gauge/Histogram constructed with a name not
                     matching ``ray_tpu_[a-z0-9_]+``
  M2 undocumented    an exported metric name missing from the COMPONENTS.md
                     Observability table (the doc is the metrics contract)
  M3 hot-path        a hot-path module (scheduler/batching/object store/
                     worker/wire layers) importing util.metrics or calling
                     Metric methods (.inc/.observe) directly — hot paths bump
                     plain ints; materialization belongs in telemetry.py at
                     snapshot cadence
  M4 alert-rule      a DEFAULT_ALERT_RULES entry (timeseries.py, parsed as a
                     pure literal) whose rule name or referenced metric name
                     is missing from the COMPONENTS.md Observability tables —
                     a stale rule name fails the run (the failpoint-table
                     discipline, applied to the alert pack)
  M5 event-kind      a cluster-event kind that is either used at an emit
                     site (emit_event / _emit_event / append_cluster_event
                     with a literal kind) without being registered in
                     events.EVENT_KINDS, or registered but missing from the
                     COMPONENTS.md events table

`.set()` is not policed: the name collides with threading.Event.set, and the
import ban (M3) already keeps Metric objects out of hot modules entirely.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from ray_tpu.devtools.astutil import (
    Package, Violation, call_name, const_str, make_key,
)

METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
NAME_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")

# Modules on the task hot path: one frame per message/object flows through
# these, so Metric-object work (dict lookups, lock, float math) is banned.
DEFAULT_HOT_MODULES = (
    "ray_tpu._private.scheduler",
    "ray_tpu._private.batching",
    "ray_tpu._private.object_store",
    "ray_tpu._private.object_transfer",
    "ray_tpu._private.worker",
    "ray_tpu._private.worker_main",
    "ray_tpu._private.serialization",
    "ray_tpu._private.protocol",
    "ray_tpu._private.gcs",
)

_METRIC_METHODS = {"inc", "observe"}

# Cluster-event emit sites whose first positional arg (the kind) is checked
# against events.EVENT_KINDS. Variable-kind forwarding (GCS.kv_event, the
# alert engine's sink) passes non-literals and is skipped by construction.
_EVENT_EMIT_FUNCS = {"emit_event", "_emit_event", "append_cluster_event"}


def _literal_assign(tree: ast.AST, var: str):
    """The pure-literal value assigned to module-level `var`, or None. Same
    contract as protocol.MESSAGE_GRAMMAR: parsed with ast.literal_eval so
    the linter never imports the runtime."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == var:
                try:
                    return ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
    return None


def _doc_text(doc_path: Optional[str]) -> Optional[str]:
    if doc_path and os.path.exists(doc_path):
        with open(doc_path, "r", encoding="utf-8") as fh:
            return fh.read()
    return None


def run(pkg: Package, hot_modules=DEFAULT_HOT_MODULES,
        doc_text: Optional[str] = None,
        doc_path: Optional[str] = None) -> List[Violation]:
    violations: List[Violation] = []
    if doc_text is None:
        doc_text = _doc_text(doc_path)

    # Registries for M4/M5, parsed as literals from their home modules
    # (absent in fixture packages: the checks simply don't arm).
    alert_rules = None
    event_kinds = None
    for module, tree in pkg.modules.items():
        if module.endswith("_private.timeseries"):
            alert_rules = _literal_assign(tree, "DEFAULT_ALERT_RULES")
            _ts_path = pkg.paths[module]
        if module.endswith("_private.events"):
            event_kinds = _literal_assign(tree, "EVENT_KINDS")
            _ev_path = pkg.paths[module]
    if alert_rules and doc_text is not None:
        for rule in alert_rules:
            if not isinstance(rule, dict):
                continue
            rname = rule.get("name", "?")
            if rname not in doc_text:
                violations.append(Violation(
                    "metrics", _ts_path, 1,
                    make_key("metrics", _ts_path, f"alert-rule.{rname}"),
                    f"default alert rule {rname!r} is not listed in the "
                    f"COMPONENTS.md alert-pack table",
                ))
            metric = rule.get("metric", "")
            if metric and metric not in doc_text:
                violations.append(Violation(
                    "metrics", _ts_path, 1,
                    make_key("metrics", _ts_path, f"alert-metric.{metric}"),
                    f"alert rule {rname!r} references metric {metric!r}, "
                    f"which is not in the COMPONENTS.md Observability table",
                ))
    if event_kinds and doc_text is not None:
        for kind in event_kinds:
            if kind not in doc_text:
                violations.append(Violation(
                    "metrics", _ev_path, 1,
                    make_key("metrics", _ev_path, f"event-kind.{kind}"),
                    f"event kind {kind!r} is registered in EVENT_KINDS but "
                    f"missing from the COMPONENTS.md events table",
                ))

    reported: Set[str] = set()
    for module, tree in pkg.modules.items():
        path = pkg.paths[module]
        hot = module in hot_modules
        for node in ast.walk(tree):
            if isinstance(node, ast.Import) and hot:
                for alias in node.names:
                    if "util.metrics" in alias.name:
                        violations.append(Violation(
                            "metrics", path, node.lineno,
                            make_key("metrics", path, "hot-import"),
                            f"hot-path module {module} imports {alias.name}: "
                            f"hot paths bump plain ints, Metric objects live "
                            f"in telemetry.py",
                        ))
                continue
            if isinstance(node, ast.ImportFrom) and hot:
                if node.module and "util.metrics" in node.module:
                    violations.append(Violation(
                        "metrics", path, node.lineno,
                        make_key("metrics", path, "hot-import"),
                        f"hot-path module {module} imports {node.module}: "
                        f"hot paths bump plain ints, Metric objects live in "
                        f"telemetry.py",
                    ))
                continue
            if not isinstance(node, ast.Call):
                continue
            recv, meth = call_name(node)
            if (
                event_kinds is not None
                and meth in _EVENT_EMIT_FUNCS
                and node.args
                and not module.endswith("_private.events")
            ):
                kind = const_str(node.args[0])
                if kind is not None and kind not in event_kinds:
                    key = make_key("metrics", path,
                                   f"event-unregistered.{kind}")
                    if key not in reported:
                        reported.add(key)
                        violations.append(Violation(
                            "metrics", path, node.lineno, key,
                            f"event kind {kind!r} is not registered in "
                            f"events.EVENT_KINDS (register it there AND in "
                            f"the COMPONENTS.md events table)",
                        ))
            if hot and meth in _METRIC_METHODS and recv is not None:
                key = make_key("metrics", path, f"hot-call.{recv}.{meth}")
                if key not in reported:
                    reported.add(key)
                    violations.append(Violation(
                        "metrics", path, node.lineno, key,
                        f"hot-path module {module} calls {recv}.{meth}(): "
                        f"metric materialization belongs in telemetry.py "
                        f"collectors, not on the hot path",
                    ))
            if meth in METRIC_CTORS and recv is None and node.args:
                name = const_str(node.args[0])
                if name is None:
                    continue
                if not NAME_RE.match(name):
                    violations.append(Violation(
                        "metrics", path, node.lineno,
                        make_key("metrics", path, f"name.{name}"),
                        f"metric name {name!r} does not match "
                        f"ray_tpu_[a-z0-9_]+",
                    ))
                elif doc_text is not None and name not in doc_text:
                    violations.append(Violation(
                        "metrics", path, node.lineno,
                        make_key("metrics", path, f"undocumented.{name}"),
                        f"metric {name!r} is not listed in the COMPONENTS.md "
                        f"Observability table",
                    ))
    return violations
