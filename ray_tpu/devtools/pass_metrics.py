"""Metrics discipline.

  M1 bad-name        a Counter/Gauge/Histogram constructed with a name not
                     matching ``ray_tpu_[a-z0-9_]+``
  M2 undocumented    an exported metric name missing from the COMPONENTS.md
                     Observability table (the doc is the metrics contract)
  M3 hot-path        a hot-path module (scheduler/batching/object store/
                     worker/wire layers) importing util.metrics or calling
                     Metric methods (.inc/.observe) directly — hot paths bump
                     plain ints; materialization belongs in telemetry.py at
                     snapshot cadence

`.set()` is not policed: the name collides with threading.Event.set, and the
import ban (M3) already keeps Metric objects out of hot modules entirely.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from ray_tpu.devtools.astutil import (
    Package, Violation, call_name, const_str, make_key,
)

METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
NAME_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")

# Modules on the task hot path: one frame per message/object flows through
# these, so Metric-object work (dict lookups, lock, float math) is banned.
DEFAULT_HOT_MODULES = (
    "ray_tpu._private.scheduler",
    "ray_tpu._private.batching",
    "ray_tpu._private.object_store",
    "ray_tpu._private.object_transfer",
    "ray_tpu._private.worker",
    "ray_tpu._private.worker_main",
    "ray_tpu._private.serialization",
    "ray_tpu._private.protocol",
    "ray_tpu._private.gcs",
)

_METRIC_METHODS = {"inc", "observe"}


def _doc_text(doc_path: Optional[str]) -> Optional[str]:
    if doc_path and os.path.exists(doc_path):
        with open(doc_path, "r", encoding="utf-8") as fh:
            return fh.read()
    return None


def run(pkg: Package, hot_modules=DEFAULT_HOT_MODULES,
        doc_text: Optional[str] = None,
        doc_path: Optional[str] = None) -> List[Violation]:
    violations: List[Violation] = []
    if doc_text is None:
        doc_text = _doc_text(doc_path)

    reported: Set[str] = set()
    for module, tree in pkg.modules.items():
        path = pkg.paths[module]
        hot = module in hot_modules
        for node in ast.walk(tree):
            if isinstance(node, ast.Import) and hot:
                for alias in node.names:
                    if "util.metrics" in alias.name:
                        violations.append(Violation(
                            "metrics", path, node.lineno,
                            make_key("metrics", path, "hot-import"),
                            f"hot-path module {module} imports {alias.name}: "
                            f"hot paths bump plain ints, Metric objects live "
                            f"in telemetry.py",
                        ))
                continue
            if isinstance(node, ast.ImportFrom) and hot:
                if node.module and "util.metrics" in node.module:
                    violations.append(Violation(
                        "metrics", path, node.lineno,
                        make_key("metrics", path, "hot-import"),
                        f"hot-path module {module} imports {node.module}: "
                        f"hot paths bump plain ints, Metric objects live in "
                        f"telemetry.py",
                    ))
                continue
            if not isinstance(node, ast.Call):
                continue
            recv, meth = call_name(node)
            if hot and meth in _METRIC_METHODS and recv is not None:
                key = make_key("metrics", path, f"hot-call.{recv}.{meth}")
                if key not in reported:
                    reported.add(key)
                    violations.append(Violation(
                        "metrics", path, node.lineno, key,
                        f"hot-path module {module} calls {recv}.{meth}(): "
                        f"metric materialization belongs in telemetry.py "
                        f"collectors, not on the hot path",
                    ))
            if meth in METRIC_CTORS and recv is None and node.args:
                name = const_str(node.args[0])
                if name is None:
                    continue
                if not NAME_RE.match(name):
                    violations.append(Violation(
                        "metrics", path, node.lineno,
                        make_key("metrics", path, f"name.{name}"),
                        f"metric name {name!r} does not match "
                        f"ray_tpu_[a-z0-9_]+",
                    ))
                elif doc_text is not None and name not in doc_text:
                    violations.append(Violation(
                        "metrics", path, node.lineno,
                        make_key("metrics", path, f"undocumented.{name}"),
                        f"metric {name!r} is not listed in the COMPONENTS.md "
                        f"Observability table",
                    ))
    return violations
