"""Thread-affinity race detection, anchored on the `_private/concurrency.py`
annotations.

  A1 any->loop call      an `@any_thread` function directly calls a
                         `@loop_thread_only` function (same-or-looser rule:
                         loop-only code may call anything; any-thread code
                         may only call any-thread / unannotated code)
  A2 unlocked shared     an instance attribute STORED (assign/augassign/
     state               subscript-store/delete) by both a loop-only method
                         and an any-thread method of the same class, where
                         either side's store is not under a `with self.<lock>`
                         block (attr names containing "lock") — and the
                         any-thread method is not `@lock_guarded`

Reads are deliberately out of scope (too many benign racy reads are part of
the design — e.g. BatchedSender's timer peeking at `_buf`); stores from both
affinities are where lost updates and torn state come from.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.astutil import (
    FuncInfo, Package, Violation, ancestors, call_name, make_key, walk_body,
)

LOOP = "loop_thread_only"
ANY = "any_thread"
LOCKED = "lock_guarded"


def _affinity(info: FuncInfo) -> Optional[str]:
    if LOOP in info.decorators:
        return LOOP
    if ANY in info.decorators:
        return ANY
    return None


def _under_self_lock(node: ast.AST) -> bool:
    """True if an ancestor `with` holds an attribute whose name mentions
    "lock" (self._lock, self._wake_lock, cls-level locks...)."""
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Attribute) and "lock" in sub.attr.lower():
                        return True
                    if isinstance(sub, ast.Name) and "lock" in sub.id.lower():
                        return True
    return False


def _self_stores(info: FuncInfo) -> Dict[str, bool]:
    """attr -> all_stores_locked for attributes of `self` this function
    stores to."""
    out: Dict[str, bool] = {}

    def note(attr: str, locked: bool) -> None:
        out[attr] = out.get(attr, True) and locked

    # walk_body, not ast.walk: a nested closure runs when (and on whatever
    # thread) it is called, so its stores must not inherit this function's
    # affinity (e.g. _cmd_pull_object's _read_and_respond pull-read thread).
    for node in walk_body(info.node):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for tgt in targets:
            attr = _self_attr_of_target(tgt)
            if attr is not None:
                note(attr, _under_self_lock(node))
    return out


def _self_attr_of_target(tgt: ast.AST) -> Optional[str]:
    # self.x = ... / self.x += ...
    if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
            and tgt.value.id == "self":
        return tgt.attr
    # self.x[k] = ... / del self.x[k]
    if isinstance(tgt, ast.Subscript):
        return _self_attr_of_target(tgt.value)
    # (a, self.x) = ...
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            got = _self_attr_of_target(e)
            if got is not None:
                return got
    return None


def run(pkg: Package, modules: Optional[Set[str]] = None) -> List[Violation]:
    infos = [
        f for f in pkg.functions.values()
        if modules is None or f.module in modules
    ]
    annotated = [f for f in infos if _affinity(f) is not None]
    violations: List[Violation] = []

    # A1: any_thread -> loop_thread_only direct calls (same class or module).
    loop_keys: Dict[Tuple[str, Optional[str], str], FuncInfo] = {
        (f.module, f.cls, f.name): f for f in annotated if _affinity(f) == LOOP
    }
    for f in annotated:
        if _affinity(f) != ANY:
            continue
        for node in walk_body(f.node):
            if not isinstance(node, ast.Call):
                continue
            recv, meth = call_name(node)
            target = None
            if recv == "self" and f.cls:
                target = loop_keys.get((f.module, f.cls, meth))
            elif recv is None:
                target = loop_keys.get((f.module, None, meth))
            if target is not None:
                violations.append(Violation(
                    "affinity", f.path, node.lineno,
                    make_key("affinity", f.path, f.qualname, f"calls={target.qualname}"),
                    f"@any_thread {f.qualname} calls @loop_thread_only "
                    f"{target.qualname} — off-thread callers would mutate "
                    f"loop-owned state",
                ))

    # A2: shared instance state stored from both affinities without locks.
    by_class: Dict[Tuple[str, str], List[FuncInfo]] = {}
    for f in annotated:
        if f.cls:
            by_class.setdefault((f.module, f.cls), []).append(f)
    for (module, cls), funcs in sorted(by_class.items()):
        stores: Dict[str, Dict[str, List[Tuple[FuncInfo, bool]]]] = {}
        for f in funcs:
            aff = _affinity(f)
            locked_ok = LOCKED in f.decorators
            for attr, all_locked in _self_stores(f).items():
                stores.setdefault(attr, {}).setdefault(aff, []).append(
                    (f, all_locked or locked_ok)
                )
        for attr, by_aff in sorted(stores.items()):
            if LOOP not in by_aff or ANY not in by_aff:
                continue
            offenders = [
                f for lst in by_aff.values() for (f, locked) in lst if not locked
            ]
            if not offenders:
                continue
            f0 = offenders[0]
            violations.append(Violation(
                "affinity", f0.path, f0.node.lineno,
                make_key("affinity", f0.path, f"{cls}.{attr}", "unlocked-shared"),
                f"{cls}.{attr} is stored by both @loop_thread_only and "
                f"@any_thread methods, and {', '.join(sorted(set(f.qualname for f in offenders)))} "
                f"store(s) it outside any self.<lock> block",
            ))
    return violations
