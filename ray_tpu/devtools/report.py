"""Shared violation reporting for the devtools CLIs (rt-lint, rt-verify,
rt-state): one allowlist loader/applier with stale-entry detection, one
summary formatter, one ``--json`` encoder.

Before this module, lint.py and verify/__init__.py each carried their own
copy of the load → apply → stale-error block, and the two CLIs each carried
their own copy of the render/summary loop; a format change had to be made
twice or the tools drifted. Everything allowlist- and output-shaped now
lives here; the passes stay pure (they return Violations, nothing else).
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

from ray_tpu.devtools.astutil import (
    Violation, apply_allowlist, load_allowlist,
)


def apply_allowlist_file(violations: List[Violation],
                         allowlist_path: str) -> Tuple[List[Violation], List[str]]:
    """Load ``allowlist_path``, suppress matching violations, and report
    format errors plus stale (unused) entries as errors. Returns the
    remaining violations (sorted) and the error strings."""
    errors: List[str] = []
    entries, fmt_errors = load_allowlist(allowlist_path)
    errors.extend(fmt_errors)
    violations, unused = apply_allowlist(violations, entries)
    for e in unused:
        errors.append(
            f"{allowlist_path}:{e.line_no}: allowlist entry no longer "
            f"matches any violation (stale — delete it): {e.key}"
        )
    violations.sort(key=lambda v: (v.pass_id, v.path, v.line))
    return violations, errors


def counts_by_pass(violations: Sequence[Violation]) -> Dict[str, int]:
    by_pass: Dict[str, int] = {}
    for v in violations:
        by_pass[v.pass_id] = by_pass.get(v.pass_id, 0) + 1
    return by_pass


def as_json(tool: str, violations: Sequence[Violation],
            errors: Sequence[str], exit_code: int) -> str:
    """Machine-readable findings: stable shape for CI diffing (tools/check.sh
    can compare runs instead of grepping human text)."""
    return json.dumps({
        "tool": tool,
        "exit_code": exit_code,
        "counts": counts_by_pass(violations),
        "violations": [
            {"pass": v.pass_id, "path": v.path, "line": v.line,
             "key": v.key, "message": v.message}
            for v in violations
        ],
        "allowlist_errors": list(errors),
    }, indent=2, sort_keys=True)


def emit(tool: str, violations: Sequence[Violation], errors: Sequence[str],
         quiet: bool = False, json_out: bool = False) -> int:
    """Print findings the one canonical way; returns the exit code (0 clean,
    1 violations or allowlist errors)."""
    rc = 1 if (violations or errors) else 0
    if json_out:
        print(as_json(tool, violations, errors, rc))
        return rc
    if not quiet:
        for v in violations:
            print(v.render())
        for e in errors:
            print(f"ALLOWLIST ERROR: {e}")
    detail = ", ".join(f"{k}={c}" for k, c in
                       sorted(counts_by_pass(violations).items()))
    status = "FAILED" if rc else "OK"
    print(f"{tool} {status}: {len(violations)} violation(s)"
          + (f" ({detail})" if detail else "")
          + (f", {len(errors)} allowlist error(s)" if errors else ""))
    return rc
