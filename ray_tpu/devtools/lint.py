"""rt-lint CLI: run the eight invariant passes over the ray_tpu tree.

Usage::

    python -m ray_tpu.devtools.lint [package_dir] [--allowlist FILE]
        [--passes protocol,blocking,affinity,config,metrics,failpoints,ownership,lifecycle]
        [-q] [--json]

Exit status: 0 = clean (after allowlist), 1 = violations / allowlist format
errors / unused allowlist entries. Designed for CI (tools/check.sh) and for
tests/test_static_analysis.py, which runs it over the live package so any
new violation fails tier-1.

The allowlist (default: lint_allowlist.txt next to this file) suppresses a
violation only with a per-line justification::

    <violation key> -- <why this one is acceptable>

Unused entries fail the run, so the file can only shrink or stay honest.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable, Dict, List

from ray_tpu.devtools import (
    pass_affinity, pass_blocking, pass_config, pass_failpoints,
    pass_lifecycle, pass_metrics, pass_ownership, pass_protocol, report,
)
from ray_tpu.devtools.astutil import Package, Violation, load_package

PASSES: Dict[str, Callable[[Package], List[Violation]]] = {
    "protocol": pass_protocol.run,
    "blocking": pass_blocking.run,
    "affinity": pass_affinity.run,
    "config": pass_config.run,
    "metrics": pass_metrics.run,
    "failpoints": pass_failpoints.run,
    "ownership": pass_ownership.run,
    "lifecycle": pass_lifecycle.run,
}

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_ALLOWLIST = os.path.join(_HERE, "lint_allowlist.txt")


def run_all(package_dir: str, passes=None, doc_path: str = None,
            allowlist_path: str = None):
    """Programmatic entry: returns (violations, allowlist_errors). Used by
    tests and the CLI alike."""
    pkg = load_package(package_dir, package_name="ray_tpu")
    if doc_path is None:
        cand = os.path.join(os.path.dirname(os.path.abspath(package_dir)),
                            "COMPONENTS.md")
        doc_path = cand if os.path.exists(cand) else None
    violations: List[Violation] = []
    for name in passes or PASSES:
        fn = PASSES[name]
        if name == "metrics":
            violations.extend(pass_metrics.run(pkg, doc_path=doc_path))
        elif name == "failpoints":
            violations.extend(pass_failpoints.run(pkg, doc_path=doc_path))
        else:
            violations.extend(fn(pkg))
    errors: List[str] = []
    if allowlist_path:
        violations, errors = report.apply_allowlist_file(violations, allowlist_path)
    violations.sort(key=lambda v: (v.pass_id, v.path, v.line))
    return violations, errors


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("package", nargs="?", default=None,
                        help="package directory to lint (default: the "
                             "ray_tpu package this tool ships in)")
    parser.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                        help="allowlist file (use /dev/null to disable)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated subset of: " + ",".join(PASSES))
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="print only the summary line")
    parser.add_argument("--json", action="store_true", dest="json_out",
                        help="emit machine-readable findings (per-pass "
                             "counts + violations + exit code) on stdout")
    ns = parser.parse_args(argv)

    package_dir = ns.package or os.path.dirname(_HERE)
    passes = ns.passes.split(",") if ns.passes else None
    if passes:
        unknown = [p for p in passes if p not in PASSES]
        if unknown:
            print(f"rt-lint: unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
            return 2

    violations, errors = run_all(package_dir, passes=passes,
                                 allowlist_path=ns.allowlist)
    return report.emit("rt-lint", violations, errors, quiet=ns.quiet,
                       json_out=ns.json_out)


if __name__ == "__main__":
    sys.exit(main())
