"""Event-loop blocking-call detection.

Builds a call graph rooted at the scheduler's loop-thread entry points
(`Scheduler._loop`, every `_cmd_*`/`_req_*` handler — the loop dispatches to
them via getattr, which no AST resolver can follow — and anything annotated
`@loop_thread_only`) and flags reachable blocking primitives:

  time.sleep / select / socket connects        (unconditional stalls)
  .recv / .recv_bytes / .accept                (unless poll()-guarded)
  .result() / .wait() / .join() / .acquire()   (when un-timed)
  zero-arg .get()                              (queue waits; dict.get has args)
  open() / shutil.copyfile / shutil.rmtree     (data-plane file I/O — spills,
                                                log files; metadata syscalls
                                                like os.unlink stay out of
                                                scope deliberately)
  subprocess.Popen / run / check_output        (process spawn)
  ray_tpu.get / ray_tpu.wait                   (re-entrant blocking API)

Edges resolved: self.method(), local/imported package functions, and —
conservatively — attribute calls whose bare name is defined exactly once in
the scanned modules (skipping common collision-prone names). Unresolvable
calls are ignored; this pass under-approximates reachability by design and
exists to catch the obvious regressions cheaply.

A violation's key is (enclosing function, primitive), line-number free; the
message carries one sample root chain for debugging.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.astutil import (
    FuncInfo, Package, Violation, ancestors, call_name, has_timeout_arg,
    imported_names, make_key, walk_body,
)

# Modules whose functions participate in the call graph (what the scheduler
# loop can actually reach; scanning all of rllib would only add name-collision
# noise).
DEFAULT_GRAPH_MODULES = (
    "ray_tpu._private.scheduler",
    "ray_tpu._private.batching",
    "ray_tpu._private.telemetry",
    "ray_tpu._private.gcs",
    "ray_tpu._private.object_store",
    "ray_tpu._private.serialization",
    "ray_tpu._private.memory_monitor",
    "ray_tpu._private.runtime_env",
    "ray_tpu._private.config",
    "ray_tpu._private.ids",
    "ray_tpu._private.protocol",
    "ray_tpu._private.concurrency",
    "ray_tpu.util.metrics",
)

# Bare method names never resolved through the unique-name fallback: too
# generic, collisions guaranteed.
_SKIP_RESOLVE = {
    "get", "put", "pop", "append", "add", "remove", "send", "close", "items",
    "values", "keys", "update", "clear", "copy", "extend", "set", "start",
    "stop", "run", "join", "wait", "result", "acquire", "release", "submit",
    "hex", "binary", "encode", "decode", "read", "write", "flush", "push",
}

_TIMED_WAIT_METHODS = {"result", "wait", "join", "acquire"}
_RECV_METHODS = {"recv", "recv_bytes", "recv_bytes_into", "accept"}
_FILE_IO_FUNCS = {"open"}
_FILE_IO_ATTRS = {("shutil", "copyfile"), ("shutil", "rmtree")}
_SUBPROCESS_ATTRS = {"Popen", "run", "call", "check_call", "check_output", "communicate"}


def _poll_guarded(node: ast.AST) -> bool:
    """True if an ancestor While/If test polls readiness — the standard
    `while conn.poll(): conn.recv_bytes()` drain shape."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.While, ast.If)):
            for sub in ast.walk(anc.test):
                if isinstance(sub, ast.Call) and call_name(sub)[1] == "poll":
                    return True
    return False


def _blocking_primitive(node: ast.Call, imports: Dict[str, str]) -> Optional[str]:
    """Name of the blocking primitive this call is, or None."""
    recv, meth = call_name(node)
    base = recv.split(".")[-1] if recv else None
    # time.sleep / imported sleep
    if meth == "sleep" and (base == "time" or imports.get("sleep", "").endswith("time.sleep")):
        return "time.sleep"
    if recv == "select" and meth == "select":
        return "select.select"
    if meth == "create_connection" or (meth == "Client" and not recv) or \
            (base == "socket" and meth == "connect"):
        return f"{meth} (connect)"
    if meth in _RECV_METHODS:
        if _poll_guarded(node):
            return None
        return f".{meth}()"
    if meth in _TIMED_WAIT_METHODS:
        if meth == "join" and node.args:
            return None  # str.join / os.path.join
        # acquire(blocking=False) is a try-lock; blocking=True (or any other
        # value) still needs a timeout to count as bounded.
        if meth == "acquire" and any(
            kw.arg == "blocking"
            and isinstance(kw.value, ast.Constant) and kw.value.value is False
            for kw in node.keywords
        ):
            return None
        if has_timeout_arg(node):
            return None
        return f".{meth}() [no timeout]"
    if meth == "get" and not node.args and not node.keywords and recv is not None:
        return ".get() [queue wait]"
    if meth in _FILE_IO_FUNCS and recv is None:
        return "open (file I/O)"
    if (base, meth) in _FILE_IO_ATTRS:
        return f"{base}.{meth} (file I/O)"
    if base == "subprocess" and meth in _SUBPROCESS_ATTRS:
        return f"subprocess.{meth}"
    if meth == "communicate":
        return ".communicate()"
    if base == "ray_tpu" and meth in ("get", "wait"):
        return f"ray_tpu.{meth}"
    return None


class _Graph:
    def __init__(self, pkg: Package, modules) -> None:
        self.pkg = pkg
        self.infos: List[FuncInfo] = [
            f for f in pkg.functions.values() if f.module in modules
        ]
        self.by_key = {f.key: f for f in self.infos}
        by_name: Dict[str, List[FuncInfo]] = {}
        for f in self.infos:
            by_name.setdefault(f.name, []).append(f)
        self.by_name = by_name
        self.imports: Dict[str, Dict[str, str]] = {
            m: imported_names(tree)
            for m, tree in pkg.modules.items() if m in modules
        }

    def edges(self, info: FuncInfo) -> Set[str]:
        out: Set[str] = set()
        imports = self.imports.get(info.module, {})
        for node in walk_body(info.node):
            if not isinstance(node, ast.Call):
                continue
            recv, meth = call_name(node)
            if not meth:
                continue
            if recv == "self" and info.cls:
                key = f"{info.module}:{info.cls}.{meth}"
                if key in self.by_key:
                    out.add(key)
                    continue
            if recv is None:
                # Local or imported function.
                key = f"{info.module}:{meth}"
                if key in self.by_key:
                    out.add(key)
                    continue
                src = imports.get(meth)
                if src:
                    mod, _, name = src.rpartition(".")
                    key = f"{mod}:{name}"
                    if key in self.by_key:
                        out.add(key)
                        continue
            if meth in _SKIP_RESOLVE:
                continue
            cands = self.by_name.get(meth, ())
            if len(cands) == 1:
                out.add(cands[0].key)
        return out


def run(pkg: Package, roots: Optional[List[str]] = None,
        graph_modules=DEFAULT_GRAPH_MODULES) -> List[Violation]:
    graph = _Graph(pkg, set(graph_modules))
    if roots is None:
        roots = []
        for f in graph.infos:
            if "loop_thread_only" in f.decorators:
                roots.append(f.key)
            elif f.cls == "Scheduler" and (
                f.name == "_loop" or f.name.startswith("_cmd_")
                or f.name.startswith("_req_")
            ):
                roots.append(f.key)
    # BFS, remembering one sample path to each function.
    came_from: Dict[str, Optional[str]] = {}
    queue: List[str] = []
    for r in roots:
        if r in graph.by_key and r not in came_from:
            came_from[r] = None
            queue.append(r)
    while queue:
        cur = queue.pop()
        for nxt in graph.edges(graph.by_key[cur]):
            if nxt not in came_from:
                came_from[nxt] = cur
                queue.append(nxt)

    violations: List[Violation] = []
    seen_keys: Set[str] = set()
    for key in came_from:
        info = graph.by_key[key]
        imports = graph.imports.get(info.module, {})
        for node in walk_body(info.node):
            if not isinstance(node, ast.Call):
                continue
            prim = _blocking_primitive(node, imports)
            if prim is None:
                continue
            vkey = make_key("blocking", info.path, info.qualname,
                            prim.split(" ")[0].strip(".()"))
            if vkey in seen_keys:
                continue
            seen_keys.add(vkey)
            chain = _chain(came_from, key)
            violations.append(Violation(
                "blocking", info.path, node.lineno, vkey,
                f"{info.qualname} calls blocking primitive {prim} on the "
                f"scheduler loop thread (reachable via {' -> '.join(chain)})",
            ))
    return violations


def _chain(came_from: Dict[str, Optional[str]], key: str) -> List[str]:
    out = []
    cur: Optional[str] = key
    while cur is not None:
        out.append(cur.split(":", 1)[1])
        cur = came_from.get(cur)
    return list(reversed(out))
