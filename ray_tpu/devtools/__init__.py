"""rt-lint: AST-based invariant analysis for the ray_tpu control plane.

Pure stdlib (ast + os): the linter parses the tree, it never imports the
runtime, so it runs in a bare venv and can't be broken by a bug it is trying
to find. Entry point::

    python -m ray_tpu.devtools.lint [paths] [--allowlist FILE]

Passes (each in its own module, all driven by lint.py):

  protocol   -- every sender site and reader dispatch loop cross-checked
                against protocol.MESSAGE_GRAMMAR (tags, arities, coverage)
  blocking   -- call graph rooted at scheduler loop-thread entry points;
                reachable blocking primitives (sleep/recv/file I/O/...) flagged
  affinity   -- @loop_thread_only/@any_thread annotations (concurrency.py)
                verified: no any->loop calls, no unlocked cross-affinity state
  config     -- every cfg.<name> access and RAY_TPU_* env read must map to a
                declared Config field or the ENV_VARS registry; dead knobs flagged
  metrics    -- metric names must match ray_tpu_* and be documented in
                COMPONENTS.md; hot-path modules must not touch Metric objects
  failpoints -- failpoint names must appear in COMPONENTS.md's table
  ownership  -- owner-path modules must not touch head tables directly

System-level verification lives in the `verify` subpackage (rt-verify:
protocol session machine, lock-order cycles, native C checks, stale-binary
guard, wire-codec fuzzing) — `python -m ray_tpu.devtools.verify`. Both
tools share the parsed-AST cache in astutil (one parse per file per
process).

Violations carry stable symbol keys (no line numbers); the checked-in
allowlist (lint_allowlist.txt) suppresses a violation only with a per-line
justification, and unused entries fail the run.
"""
