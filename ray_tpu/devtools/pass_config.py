"""Config-knob discipline.

  C1 undeclared-knob   a runtime Config attribute access that resolves to no
                       declared `Config` dataclass field (typo / removed knob)
  C2 dead-knob         a declared field never read anywhere in the tree
  C3 unknown-env       a RAY_TPU_* environment key used (read OR set) that is
                       neither `RAY_TPU_<config field>` (the documented
                       override form) nor listed in config.ENV_VARS

Config-access detection (under-approximate on purpose, zero false positives
over precision):
  - `get_config().<attr>` anywhere in the tree;
  - `<name>.<attr>` where <name> was assigned from `get_config()` or from a
    `*.config` chain in the same function;
  - `<expr>.config.<attr>` chains inside the runtime-core modules
    (CONFIG_MODULES) — rllib/serve carry their own unrelated `.config`
    objects, so the chain rule must not see them;
  - `<param>.<attr>` where the enclosing function's parameter is annotated
    `Config`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.astutil import (
    Package, Violation, ancestors, call_name, const_str, dotted, make_key,
)

# Modules where a bare `*.config.<attr>` chain means the runtime Config.
DEFAULT_CONFIG_MODULES = (
    "ray_tpu._private.scheduler",
    "ray_tpu._private.worker",
    "ray_tpu._private.worker_main",
    "ray_tpu._private.node_daemon",
    "ray_tpu._private.batching",
    "ray_tpu._private.retry",
    "ray_tpu._private.telemetry",
    "ray_tpu._private.timeseries",
    "ray_tpu._private.jobs",
    "ray_tpu._private.object_store",
    "ray_tpu._private.head",
    "ray_tpu._private.launch",
    "ray_tpu._private.config",
)

_CONFIG_METHODS = {"apply_overrides"}


def _declared(pkg: Package) -> Tuple[Optional[Set[str]], Optional[Set[str]], Optional[str]]:
    """(fields, env_vars, path) parsed from the Config dataclass + ENV_VARS
    registry in config.py."""
    tree = pkg.module_of("ray_tpu._private.config") or pkg.module_of("config.py")
    if tree is None:
        return None, None, None
    fields: Optional[Set[str]] = None
    env_vars: Optional[Set[str]] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            fields = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "ENV_VARS":
                    try:
                        val = ast.literal_eval(node.value)
                        env_vars = set(val) if not isinstance(val, dict) else set(val.keys())
                    except ValueError:
                        pass
    path = None
    for mod, p in pkg.paths.items():
        if mod.endswith("config") or p.endswith("config.py"):
            path = p
            break
    return fields, env_vars, path


def _config_receivers(fn_node: ast.AST, chain_ok: bool) -> Set[str]:
    """Local names holding the runtime Config inside one function: assigned
    from get_config() (anywhere), or — inside runtime-core modules only
    (`chain_ok`) — assigned from a `... .config` chain, named cfg/config as
    a parameter, or annotated `Config` (rllib/serve have their own config
    objects under the same names, so these rules must not see them)."""
    names: Set[str] = set()
    args = getattr(fn_node, "args", None)
    if args is not None and chain_ok:
        for a in list(args.args) + list(args.kwonlyargs):
            ann = a.annotation
            ann_s = dotted(ann) if ann is not None else None
            if ann_s is None and ann is not None:
                ann_s = const_str(ann)  # "Config" string annotations
            if ann_s and ann_s.split(".")[-1] == "Config":
                names.add(a.arg)
            elif a.arg in ("cfg", "config"):
                names.add(a.arg)
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            src = node.value
            if isinstance(src, ast.Call) and call_name(src)[1] == "get_config":
                names.add(node.targets[0].id)
            elif chain_ok:
                d = dotted(src)
                if d and d.split(".")[-1] == "config":
                    names.add(node.targets[0].id)
    return names


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _iter_config_accesses(pkg: Package, chain_modules: Set[str]):
    """Yield (module, path, attr_name, lineno) for every detected runtime
    Config attribute access."""
    for module, tree in pkg.modules.items():
        path = pkg.paths[module]
        chain_ok = module in chain_modules
        recv_cache: Dict[ast.AST, Set[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute) or not isinstance(node.ctx, ast.Load):
                continue
            # Skip the inner `.config` of a longer chain (x.config.attr visits
            # both `x.config.attr` and `x.config`).
            parent = getattr(node, "_rt_parent", None)
            if isinstance(parent, ast.Attribute):
                continue
            base = node.value
            attr = node.attr
            hit = False
            if isinstance(base, ast.Call) and call_name(base)[1] == "get_config":
                hit = True
            elif isinstance(base, ast.Attribute) and base.attr == "config" and chain_ok:
                hit = True
            elif isinstance(base, ast.Name):
                fn = _enclosing_function(node)
                if fn is not None:
                    if fn not in recv_cache:
                        recv_cache[fn] = _config_receivers(fn, chain_ok)
                    if base.id in recv_cache[fn]:
                        hit = True
            if hit:
                yield module, path, attr, node.lineno


def _iter_env_uses(pkg: Package):
    """Yield (module, path, env_key, lineno) for RAY_TPU_* keys used with
    os.environ / os.getenv (reads, membership tests, and writes)."""
    for module, tree in pkg.modules.items():
        path = pkg.paths[module]
        for node in ast.walk(tree):
            key = None
            if isinstance(node, ast.Call):
                recv, meth = call_name(node)
                env_call = (
                    (recv and recv.endswith("environ") and meth in ("get", "pop", "setdefault"))
                    or (meth == "getenv")
                )
                if env_call and node.args:
                    key = const_str(node.args[0])
            elif isinstance(node, ast.Subscript):
                base = dotted(node.value)
                if base and (base.endswith("environ") or base in ("env", "envb")):
                    key = const_str(node.slice)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)):
                cmp_base = dotted(node.comparators[0])
                if cmp_base and cmp_base.endswith("environ"):
                    key = const_str(node.left)
            if key and key.startswith("RAY_TPU_"):
                yield module, path, key, node.lineno


def run(pkg: Package, fields: Optional[Set[str]] = None,
        env_vars: Optional[Set[str]] = None,
        config_modules=DEFAULT_CONFIG_MODULES,
        check_dead: bool = True) -> List[Violation]:
    violations: List[Violation] = []
    d_fields, d_env, cfg_path = _declared(pkg)
    if fields is None:
        fields = d_fields
    if env_vars is None:
        env_vars = d_env if d_env is not None else set()
    if fields is None:
        return [Violation("config", "<config>", 0,
                          make_key("config", "config.py", "missing-config"),
                          "Config dataclass not found in the tree")]

    seen_fields: Set[str] = set()
    reported: Set[str] = set()
    for module, path, attr, lineno in _iter_config_accesses(pkg, set(config_modules)):
        if attr.startswith("__") or attr in _CONFIG_METHODS:
            continue
        if attr in fields:
            seen_fields.add(attr)
            continue
        key = make_key("config", path, f"cfg.{attr}")
        if key in reported:
            continue
        reported.add(key)
        violations.append(Violation(
            "config", path, lineno, key,
            f"access to undeclared config knob cfg.{attr} (no such Config field)",
        ))

    for module, path, env_key, lineno in _iter_env_uses(pkg):
        suffix = env_key[len("RAY_TPU_"):]
        if suffix in fields:
            seen_fields.add(suffix)
            continue
        if env_key in env_vars:
            continue
        key = make_key("config", path, f"env.{env_key}")
        if key in reported:
            continue
        reported.add(key)
        violations.append(Violation(
            "config", path, lineno, key,
            f"environment key {env_key} is neither a RAY_TPU_<Config field> "
            f"override nor declared in config.ENV_VARS",
        ))

    if check_dead:
        for field_name in sorted(fields - seen_fields):
            violations.append(Violation(
                "config", cfg_path or "config.py", 0,
                make_key("config", cfg_path or "config.py", f"dead.{field_name}"),
                f"Config.{field_name} is declared but never read anywhere "
                f"(dead knob)",
            ))
    return violations
