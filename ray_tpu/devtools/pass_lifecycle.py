"""Lifecycle-machine pass: cross-check every state write/comparison site in
the covered modules against lifecycle.LIFECYCLE_SPEC (rt-state's static side).

The spec is a pure literal (the MESSAGE_GRAMMAR pattern): it is extracted
from ``_private/lifecycle.py``'s AST with ``ast.literal_eval`` — linting
never imports the runtime. A *site* is attributed to a machine three ways,
most-specific first:

 - the ``lifecycle.step("machine", old, new)`` call's literal machine arg;
 - the enclosing class, when it is one of the machine's ``classes``
   (dataclass defaults, ``self.<attr> = ...`` in ``__init__``);
 - the receiver name: ``(module, receiver, attr)`` against the machine's
   ``receivers`` (``rec.state``, ``wh.health``, ...).

Checks:
  L1 write-bypasses-step   attributed transition write not going through
                           lifecycle.step() (initial assignments exempt)
  L2 initial-mismatch      a machine class's default/__init__ assignment is
                           not the spec's initial state
  L3 unknown-state         step() targets a state (or names a machine) the
                           spec does not declare
  L4 unauthorized-module   step() driven from a module the spec does not
                           authorize for any edge into that target state;
                           also covers a step() whose receiver maps to a
                           DIFFERENT machine than its literal machine arg
  L5 unknown-state-compare comparison of an attributed receiver's state
                           against a name the spec does not declare
  L6 unreachable-state     a spec state no code ever writes or compares
                           (machines with a dynamic-target step() write are
                           exempt — their targets are not statically visible)
  L7 unattributed-write    a write to a covered attr in a covered module
                           that no machine claims (new machine or typo'd
                           receiver; allowlist with a justification if the
                           attr genuinely is not a lifecycle machine)
  L8 spec-incoherent       terminal state with outgoing edges, two machines
                           claiming one (class, attr) or (module, receiver,
                           attr), or a step() whose old-state arg is not the
                           written attribute itself
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.astutil import (
    Package, Violation, ancestors, call_name, const_str, dotted,
    imported_names, make_key,
)

_PASS = "lifecycle"


def _spec_from_source(pkg: Package) -> Optional[dict]:
    """ast.literal_eval LIFECYCLE_SPEC out of lifecycle.py's AST."""
    tree = pkg.module_of("ray_tpu._private.lifecycle") or pkg.module_of("lifecycle.py")
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "LIFECYCLE_SPEC":
                    try:
                        return ast.literal_eval(node.value)
                    except ValueError:
                        return None
    return None


def _machine_states(machine: dict) -> Set[str]:
    states = {machine["initial"]}
    states.update(machine.get("terminal", ()))
    for old, outs in machine.get("transitions", {}).items():
        states.add(old)
        states.update(outs)
    return states


def _enclosing_qualname(node: ast.AST) -> str:
    fn = None
    cls = None
    for anc in ancestors(node):
        if fn is None and isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = anc.name
        if cls is None and isinstance(anc, ast.ClassDef):
            cls = anc.name
    if cls and fn:
        return f"{cls}.{fn}"
    return fn or cls or "<module>"


def _enclosing_class(node: ast.AST) -> Optional[str]:
    for anc in ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc.name
    return None


def _enclosing_func_name(node: ast.AST) -> Optional[str]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc.name
    return None


def _is_step_call(node: ast.AST, imports: Dict[str, str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    recv, meth = call_name(node)
    if meth != "step":
        return False
    if recv is not None:
        return recv == "lifecycle" or recv.endswith(".lifecycle")
    return imports.get("step", "").endswith("lifecycle.step")


def _state_literals(node: ast.AST) -> Optional[List[str]]:
    """Literal state names a to-state expression can evaluate to: a string
    constant, or an IfExp whose arms are both literal (the
    ``"FINISHED" if ok else "FAILED"`` idiom). None = dynamic."""
    s = const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.IfExp):
        arms = []
        for arm in (node.body, node.orelse):
            got = _state_literals(arm)
            if got is None:
                return None
            arms.extend(got)
        return arms
    return None


class _SpecTables:
    def __init__(self, spec: dict):
        self.spec = spec
        self.states: Dict[str, Set[str]] = {}
        self.targets: Dict[str, Set[str]] = {}          # states with an in-edge
        self.drivers_into: Dict[str, Dict[str, Set[str]]] = {}  # machine -> state -> modules
        self.by_class: Dict[Tuple[str, str], str] = {}  # (class, attr) -> machine
        self.by_recv: Dict[Tuple[str, str, str], str] = {}  # (module, recv, attr) -> machine
        self.module_attrs: Dict[str, Set[str]] = {}     # module -> covered attrs
        self.ambiguous: List[str] = []
        for name, m in spec.items():
            self.states[name] = _machine_states(m)
            tgt: Set[str] = set()
            into: Dict[str, Set[str]] = {}
            for old, outs in m.get("transitions", {}).items():
                for new, mods in outs.items():
                    tgt.add(new)
                    into.setdefault(new, set()).update(mods)
            self.targets[name] = tgt
            self.drivers_into[name] = into
            for cls in m.get("classes", ()):
                key = (cls, m["attr"])
                if key in self.by_class and self.by_class[key] != name:
                    self.ambiguous.append(
                        f"class {cls}.{m['attr']} claimed by both "
                        f"{self.by_class[key]!r} and {name!r}")
                self.by_class[key] = name
            for mod in m.get("modules", ()):
                self.module_attrs.setdefault(mod, set()).add(m["attr"])
                for recv in m.get("receivers", ()):
                    rkey = (mod, recv, m["attr"])
                    if rkey in self.by_recv and self.by_recv[rkey] != name:
                        self.ambiguous.append(
                            f"receiver {mod}:{recv}.{m['attr']} claimed by "
                            f"both {self.by_recv[rkey]!r} and {name!r}")
                    self.by_recv[rkey] = name


def run(pkg: Package, spec: Optional[dict] = None) -> List[Violation]:
    violations: List[Violation] = []
    if spec is None:
        spec = _spec_from_source(pkg)
    if not spec:
        return [Violation(_PASS, "<spec>", 0,
                          make_key(_PASS, "lifecycle.py", "missing-spec"),
                          "LIFECYCLE_SPEC not found / not a literal in "
                          "_private/lifecycle.py")]

    tables = _SpecTables(spec)

    # L8: spec-level coherence.
    for msg in tables.ambiguous:
        violations.append(Violation(
            _PASS, "lifecycle.py", 0,
            make_key(_PASS, "lifecycle.py", "spec", "ambiguous"),
            f"LIFECYCLE_SPEC is ambiguous: {msg}"))
    for name, m in spec.items():
        for term in m.get("terminal", ()):
            if m.get("transitions", {}).get(term):
                violations.append(Violation(
                    _PASS, "lifecycle.py", 0,
                    make_key(_PASS, "lifecycle.py", f"machine={name}",
                             f"state={term}", "terminal-out-edge"),
                    f"machine {name!r}: terminal state {term!r} has outgoing "
                    f"transitions"))

    # machine -> states seen written or compared anywhere (for L6), and
    # machines with at least one dynamic-target step (exempt from L6).
    seen_states: Dict[str, Set[str]] = {name: set() for name in spec}
    dynamic_write: Set[str] = set()
    for name, m in spec.items():
        seen_states[name].add(m["initial"])  # defaults checked per class below

    for module, tree in pkg.modules.items():
        attrs = tables.module_attrs.get(module)
        if not attrs:
            continue
        path = pkg.paths.get(module, module)
        imports = imported_names(tree)

        for node in ast.walk(tree):
            # ----------------------------------------------- class defaults
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                cls = _enclosing_class(node)
                if cls is None or node.value is None:
                    continue
                machine = tables.by_class.get((cls, node.target.id))
                if machine is None:
                    continue
                qual = f"{cls}.{node.target.id}"
                default = const_str(node.value)
                if default != spec[machine]["initial"]:
                    violations.append(Violation(
                        _PASS, path, node.lineno,
                        make_key(_PASS, path, qual, f"machine={machine}",
                                 "initial-mismatch"),
                        f"{qual} defaults to {default!r}, but machine "
                        f"{machine!r} starts in "
                        f"{spec[machine]['initial']!r} (L2)"))
                continue

            # ------------------------------------------------------ writes
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and node.targets[0].attr in attrs:
                tgt = node.targets[0]
                attr = tgt.attr
                recv = dotted(tgt.value)
                qual = _enclosing_qualname(node)
                encl_cls = _enclosing_class(node)

                recv_machine = None
                if recv == "self" and encl_cls is not None:
                    recv_machine = tables.by_class.get((encl_cls, attr))
                elif recv is not None:
                    recv_machine = tables.by_recv.get((module, recv, attr))

                if _is_step_call(node.value, imports):
                    call = node.value
                    mlit = const_str(call.args[0]) if call.args else None
                    if mlit is None:
                        violations.append(Violation(
                            _PASS, path, node.lineno,
                            make_key(_PASS, path, qual, "step-dynamic-machine"),
                            f"{qual}: lifecycle.step() machine argument must "
                            f"be a string literal (L3)"))
                        continue
                    if mlit not in spec:
                        violations.append(Violation(
                            _PASS, path, node.lineno,
                            make_key(_PASS, path, qual, f"machine={mlit}",
                                     "unknown-machine"),
                            f"{qual}: lifecycle.step() names machine "
                            f"{mlit!r}, not in LIFECYCLE_SPEC (L3)"))
                        continue
                    if recv_machine is not None and recv_machine != mlit:
                        violations.append(Violation(
                            _PASS, path, node.lineno,
                            make_key(_PASS, path, qual, f"machine={mlit}",
                                     "receiver-mismatch"),
                            f"{qual}: step({mlit!r}, ...) written to "
                            f"{recv}.{attr}, which the spec attributes to "
                            f"machine {recv_machine!r} (L4)"))
                    # The old-state arg must be the attribute being written:
                    # step() checks the REAL edge only if it reads the live
                    # value.
                    if len(call.args) >= 2:
                        old_arg = call.args[1]
                        if isinstance(old_arg, ast.Attribute) and (
                            old_arg.attr != attr or dotted(old_arg.value) != recv
                        ):
                            violations.append(Violation(
                                _PASS, path, node.lineno,
                                make_key(_PASS, path, qual, f"machine={mlit}",
                                         "old-arg-mismatch"),
                                f"{qual}: step() old-state arg is "
                                f"{dotted(old_arg.value)}.{old_arg.attr}, not "
                                f"the written {recv}.{attr} (L8)"))
                    news = _state_literals(call.args[2]) if len(call.args) >= 3 else None
                    if news is None:
                        # Dynamic target: the runtime monitor still checks the
                        # real edge; statically only authorization is visible.
                        dynamic_write.add(mlit)
                        if module not in spec[mlit].get("modules", ()):
                            violations.append(Violation(
                                _PASS, path, node.lineno,
                                make_key(_PASS, path, qual, f"machine={mlit}",
                                         "unauthorized-module"),
                                f"{qual}: module {module} drives machine "
                                f"{mlit!r} but is not authorized for it (L4)"))
                        continue
                    for new in news:
                        if new not in tables.states[mlit]:
                            violations.append(Violation(
                                _PASS, path, node.lineno,
                                make_key(_PASS, path, qual, f"machine={mlit}",
                                         f"state={new}", "unknown-state"),
                                f"{qual}: step() targets state {new!r}, which "
                                f"machine {mlit!r} does not declare (L3)"))
                            continue
                        seen_states[mlit].add(new)
                        if new not in tables.targets[mlit]:
                            violations.append(Violation(
                                _PASS, path, node.lineno,
                                make_key(_PASS, path, qual, f"machine={mlit}",
                                         f"state={new}", "undeclared-transition"),
                                f"{qual}: no declared transition of machine "
                                f"{mlit!r} ends in {new!r} (L1)"))
                        elif module not in tables.drivers_into[mlit].get(new, ()):
                            violations.append(Violation(
                                _PASS, path, node.lineno,
                                make_key(_PASS, path, qual, f"machine={mlit}",
                                         f"state={new}", "unauthorized-module"),
                                f"{qual}: module {module} is not authorized "
                                f"to drive machine {mlit!r} into {new!r} (L4)"))
                    continue

                # Plain (non-step) write.
                if recv_machine is None:
                    violations.append(Violation(
                        _PASS, path, node.lineno,
                        make_key(_PASS, path, qual, f"attr={attr}",
                                 "unattributed-write"),
                        f"{qual} writes {recv or '<expr>'}.{attr} in a "
                        f"covered module, but no machine claims it (L7)"))
                    continue
                machine = recv_machine
                initial = spec[machine]["initial"]
                is_init_site = (
                    recv == "self"
                    and encl_cls in spec[machine].get("classes", ())
                    and _enclosing_func_name(node) == "__init__"
                )
                lit = const_str(node.value)
                if is_init_site:
                    if lit != initial:
                        violations.append(Violation(
                            _PASS, path, node.lineno,
                            make_key(_PASS, path, qual, f"machine={machine}",
                                     "initial-mismatch"),
                            f"{qual} initializes {attr} to {lit!r}, but "
                            f"machine {machine!r} starts in {initial!r} (L2)"))
                    else:
                        seen_states[machine].add(lit)
                    continue
                violations.append(Violation(
                    _PASS, path, node.lineno,
                    make_key(_PASS, path, qual, f"machine={machine}",
                             f"state={lit}" if lit else "state=<dynamic>",
                             "bypasses-step"),
                    f"{qual} writes {recv}.{attr} (machine {machine!r}) "
                    f"directly; transition writes must go through "
                    f"lifecycle.step() (L1)"))
                if lit is not None and lit in tables.states[machine]:
                    seen_states[machine].add(lit)
                continue

            # ------------------------------------------------- comparisons
            if isinstance(node, ast.Compare):
                sides = [node.left, *node.comparators]
                attr_side = None
                for side in sides:
                    if isinstance(side, ast.Attribute) and side.attr in attrs:
                        recv = dotted(side.value)
                        encl_cls = _enclosing_class(node)
                        if recv == "self" and encl_cls is not None:
                            m = tables.by_class.get((encl_cls, side.attr))
                        elif recv is not None:
                            m = tables.by_recv.get((module, recv, side.attr))
                        else:
                            m = None
                        if m is not None:
                            attr_side = (side, m)
                            break
                if attr_side is None:
                    continue
                side, machine = attr_side
                qual = _enclosing_qualname(node)
                lits: List[str] = []
                for other in sides:
                    if other is side:
                        continue
                    s = const_str(other)
                    if s is not None:
                        lits.append(s)
                    elif isinstance(other, (ast.Tuple, ast.List, ast.Set)):
                        lits.extend(
                            es for es in (const_str(e) for e in other.elts)
                            if es is not None)
                for s in lits:
                    if s not in tables.states[machine]:
                        violations.append(Violation(
                            _PASS, path, node.lineno,
                            make_key(_PASS, path, qual, f"machine={machine}",
                                     f"state={s}", "unknown-state-compare"),
                            f"{qual} compares {dotted(side.value)}.{side.attr} "
                            f"(machine {machine!r}) against undeclared state "
                            f"{s!r} (L5)"))
                    else:
                        seen_states[machine].add(s)

    # L6: spec states nothing ever writes or compares.
    for name, m in spec.items():
        if name in dynamic_write:
            continue
        for state in sorted(tables.states[name] - seen_states[name]):
            violations.append(Violation(
                _PASS, "lifecycle.py", 0,
                make_key(_PASS, "lifecycle.py", f"machine={name}",
                         f"state={state}", "unreachable"),
                f"machine {name!r} declares state {state!r}, but no covered "
                f"code ever writes or compares it (L6)"))
    return violations
