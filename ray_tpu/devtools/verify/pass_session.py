"""Session-machine pass: check the stateful protocol rules that per-site
arity checks (rt-lint `protocol`) cannot see.

Reads protocol.SESSION_SPEC + MESSAGE_GRAMMAR as literals straight from the
AST (never imports the runtime) and checks:

  S1 spec-tag-unknown   a pair/stream tag in SESSION_SPEC that MESSAGE_GRAMMAR
                        does not define (spec drift)
  S2 pair-direction     a reply whose wire direction is not the reverse of
                        its request's (token pairing across mismatched
                        connections can never work)
  S3 role-violation     a sender site in module M emitting a tag whose
                        grammar direction names a role M does not speak
                        (e.g. worker code sending a head->worker tag)
  S4 module-unmapped    a module with sender sites but no module_roles entry
                        (new protocol speakers must declare their role)
  S5 stream-coverage    a grammar tag that shares a stream's tag prefix
                        ("transfer_") but is not part of the stream spec —
                        a streaming frame outside the machine is unmonitored
  S6 reply-unread       a pair whose reply tag has no required reader: the
                        token would be sent into a void

The "dir" field of MESSAGE_GRAMMAR is thereby ENFORCED, not documentation:
its sender side ("worker" of "worker->head"; "worker+driver" splits on "+";
"any"/"handshake" always allowed) must cover every real sender site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu.devtools.astutil import Package, Violation, make_key
from ray_tpu.devtools.pass_protocol import (
    DEFAULT_SENDER_MODULES, _collect_senders, _grammar_from_source,
)


def _literal_from_source(pkg: Package, names) -> Dict[str, object]:
    """ast.literal_eval module-level assignments out of protocol.py."""
    tree = pkg.module_of("ray_tpu._private.protocol") or pkg.module_of("protocol.py")
    out: Dict[str, object] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        targets = ()
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = (node.target,), node.value
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id in names:
                try:
                    out[tgt.id] = ast.literal_eval(value)
                except ValueError:
                    pass
    return out


def sender_roles(direction: str) -> Set[str]:
    """The role set allowed to SEND a tag with this grammar direction."""
    if direction in ("handshake", "any"):
        return {"any"}
    src = direction.split("->", 1)[0]
    return set(src.split("+"))


def run(pkg: Package, grammar: Optional[dict] = None,
        spec: Optional[dict] = None,
        sender_modules=DEFAULT_SENDER_MODULES) -> List[Violation]:
    violations: List[Violation] = []
    if grammar is None:
        grammar, _ = _grammar_from_source(pkg)
    if spec is None:
        spec = _literal_from_source(pkg, ("SESSION_SPEC",)).get("SESSION_SPEC")
    if not grammar:
        return []  # pass_protocol already reports the missing grammar
    if not isinstance(spec, dict):
        return [Violation(
            "session", "protocol.py", 0,
            make_key("session", "protocol.py", "missing-spec"),
            "SESSION_SPEC not found / not a literal in protocol.py",
        )]

    pairs = spec.get("pairs", {})
    streams = spec.get("streams", {})
    module_roles = spec.get("module_roles", {})

    # S1 + S2 + S6: pair coherence.
    for req_tag, pair in sorted(pairs.items()):
        reply_tag = pair.get("reply")
        for tag in (req_tag, reply_tag):
            if tag not in grammar:
                violations.append(Violation(
                    "session", "protocol.py", 0,
                    make_key("session", "protocol.py", f"tag={tag}", "spec-unknown"),
                    f"SESSION_SPEC pair {req_tag!r}->{reply_tag!r} names tag "
                    f"{tag!r} which is not in MESSAGE_GRAMMAR",
                ))
        if req_tag not in grammar or reply_tag not in grammar:
            continue
        req_dir = grammar[req_tag].get("dir", "any")
        rep_dir = grammar[reply_tag].get("dir", "any")
        if not _direction_reverses(req_dir, rep_dir):
            violations.append(Violation(
                "session", "protocol.py", 0,
                make_key("session", "protocol.py", f"pair={req_tag}", "direction"),
                f"pair {req_tag!r} ({req_dir}) -> {reply_tag!r} ({rep_dir}): "
                f"reply direction does not reverse the request's",
            ))
        if not grammar[reply_tag].get("readers"):
            violations.append(Violation(
                "session", "protocol.py", 0,
                make_key("session", "protocol.py", f"pair={req_tag}", "reply-unread"),
                f"pair {req_tag!r}: reply tag {reply_tag!r} has no required "
                f"reader in MESSAGE_GRAMMAR",
            ))

    # S1 + S5: stream coherence and coverage.
    for name, st in sorted(streams.items()):
        tags = [st.get("open")] + list(st.get("data", ())) + list(st.get("close", ()))
        for tag in tags:
            if tag not in grammar:
                violations.append(Violation(
                    "session", "protocol.py", 0,
                    make_key("session", "protocol.py", f"tag={tag}", "spec-unknown"),
                    f"SESSION_SPEC stream {name!r} names tag {tag!r} which is "
                    f"not in MESSAGE_GRAMMAR",
                ))
        prefix = f"{name}_"
        for tag in sorted(grammar):
            if tag.startswith(prefix) and tag not in tags:
                violations.append(Violation(
                    "session", "protocol.py", 0,
                    make_key("session", "protocol.py", f"tag={tag}", "stream-coverage"),
                    f"grammar tag {tag!r} matches stream {name!r}'s prefix but "
                    f"is not part of its SESSION_SPEC sequence",
                ))

    # S3 + S4: role conformance of every sender site.
    senders = _collect_senders(pkg, sender_modules)
    unmapped: Set[str] = set()
    import os

    for tag, _arity, path, line, qual in senders:
        base = os.path.basename(path)
        roles = module_roles.get(base)
        if roles is None:
            if base not in unmapped:
                unmapped.add(base)
                violations.append(Violation(
                    "session", path, line,
                    make_key("session", path, "module-unmapped"),
                    f"{base} has wire sender sites but no SESSION_SPEC "
                    f"module_roles entry",
                ))
            continue
        spec_entry = grammar.get(tag)
        if spec_entry is None:
            continue  # pass_protocol reports unknown tags
        allowed = sender_roles(spec_entry.get("dir", "any"))
        if "any" in allowed or "any" in roles:
            continue
        if not allowed.intersection(roles):
            violations.append(Violation(
                "session", path, line,
                make_key("session", path, qual, f"tag={tag}", "role"),
                f"{qual} ({base}: {'/'.join(roles)}) sends {tag!r}, which "
                f"only {'/'.join(sorted(allowed))} may speak "
                f"(dir {spec_entry.get('dir')!r})",
            ))
    return violations


def _direction_reverses(req_dir: str, rep_dir: str) -> bool:
    """True when the reply flows opposite to the request. "any" on either
    side of either direction matches everything on that side."""
    if req_dir in ("handshake", "any") or rep_dir in ("handshake", "any"):
        return True
    if "->" not in req_dir or "->" not in rep_dir:
        return False
    req_src, req_dst = req_dir.split("->", 1)
    rep_src, rep_dst = rep_dir.split("->", 1)

    def _m(a: str, b: str) -> bool:
        sa, sb = set(a.split("+")), set(b.split("+"))
        return "any" in sa or "any" in sb or bool(sa & sb)

    return _m(req_src, rep_dst) and _m(req_dst, rep_src)
