"""rt-verify CLI.

Usage::

    python -m ray_tpu.devtools.verify [package_dir]
        [--passes session,lockorder,native,stale] [--allowlist FILE] [-q]
        [--json] [--fuzz N] [--fuzz-seed S] [--corpus DIR]
        [--explore SCENARIOS] [--explore-budget N] [--explore-seed S]

Default: the four static passes over the shipped package (allowlisted).
``--fuzz N`` additionally runs N structure-aware mutation cases per codec
against both wire decoders (corpus replay first; crashers persisted under
<corpus>/crashers/ and named in the failure). ``--explore`` additionally
runs rt-state's interleaving exploration over the named scenarios (or
``all``): real scheduler handlers, virtual transport, systematic delivery /
crash orderings — corpus replay first, then bounded DFS.

Exit status: 0 clean, 1 violations / allowlist errors / fuzz failure /
exploration failure, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from ray_tpu.devtools import report
from ray_tpu.devtools.verify import DEFAULT_ALLOWLIST, PASS_NAMES, run_all

_HERE = os.path.dirname(os.path.abspath(__file__))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.verify", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("package", nargs="?", default=None)
    parser.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    parser.add_argument("--passes", default=None,
                        help="comma-separated subset of: " + ",".join(PASS_NAMES)
                             + " (or 'none' to skip statics, e.g. with --fuzz)")
    parser.add_argument("--fuzz", type=int, default=0, metavar="N",
                        help="also fuzz both wire codecs with N cases each")
    parser.add_argument("--fuzz-seed", type=int, default=20260804)
    parser.add_argument("--corpus", default=None,
                        help="fuzz corpus dir (default tools/fuzz_corpus)")
    parser.add_argument("-q", "--quiet", action="store_true")
    parser.add_argument("--json", action="store_true", dest="json_out",
                        help="emit machine-readable findings on stdout")
    parser.add_argument("--explore", default=None, metavar="SCENARIOS",
                        help="run interleaving exploration over a "
                             "comma-separated scenario list (or 'all')")
    parser.add_argument("--explore-budget", type=int, default=400,
                        help="max schedules explored per scenario")
    parser.add_argument("--explore-seed", type=int, default=20260807)
    ns = parser.parse_args(argv)

    package_dir = ns.package or os.path.dirname(os.path.dirname(_HERE))
    passes = ns.passes.split(",") if ns.passes else None
    if ns.passes == "none":
        passes = []  # fuzz-only / explicit no-op: don't re-run the statics
    elif passes:
        unknown = [p for p in passes if p not in PASS_NAMES]
        if unknown:
            print(f"rt-verify: unknown pass(es): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    if passes == []:
        # Fuzz-only mode: no static passes, and no allowlist application
        # (every entry would spuriously count as stale against zero
        # violations).
        violations, errors = [], []
    else:
        violations, errors = run_all(package_dir, passes=passes,
                                     allowlist_path=ns.allowlist)
    rc = report.emit("rt-verify", violations, errors, quiet=ns.quiet,
                     json_out=ns.json_out)

    if ns.fuzz > 0:
        from ray_tpu.devtools.verify import fuzz_wire

        try:
            fuzz_wire.run_fuzz(
                rounds=ns.fuzz, seed=ns.fuzz_seed,
                corpus_dir=ns.corpus or fuzz_wire.DEFAULT_CORPUS,
                quiet=ns.quiet,
            )
        except fuzz_wire.FuzzFailure as e:
            print(f"rt-verify FUZZ FAILED: {e}")
            return 1

    if ns.explore is not None:
        from ray_tpu.devtools.verify import explore

        names = (list(explore.SCENARIOS) if ns.explore == "all"
                 else ns.explore.split(","))
        unknown = [s for s in names if s not in explore.SCENARIOS]
        if unknown:
            print(f"rt-verify: unknown scenario(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        ok = explore.run_sweep(names, budget=ns.explore_budget,
                               seed=ns.explore_seed, quiet=ns.quiet)
        if not ok:
            print("rt-verify EXPLORE FAILED")
            return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
