"""rt-verify CLI.

Usage::

    python -m ray_tpu.devtools.verify [package_dir]
        [--passes session,lockorder,native,stale] [--allowlist FILE] [-q]
        [--fuzz N] [--fuzz-seed S] [--corpus DIR]

Default: the four static passes over the shipped package (allowlisted).
``--fuzz N`` additionally runs N structure-aware mutation cases per codec
against both wire decoders (corpus replay first; crashers persisted under
<corpus>/crashers/ and named in the failure).

Exit status: 0 clean, 1 violations / allowlist errors / fuzz failure,
2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict

from ray_tpu.devtools.verify import DEFAULT_ALLOWLIST, PASS_NAMES, run_all

_HERE = os.path.dirname(os.path.abspath(__file__))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.verify", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("package", nargs="?", default=None)
    parser.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    parser.add_argument("--passes", default=None,
                        help="comma-separated subset of: " + ",".join(PASS_NAMES)
                             + " (or 'none' to skip statics, e.g. with --fuzz)")
    parser.add_argument("--fuzz", type=int, default=0, metavar="N",
                        help="also fuzz both wire codecs with N cases each")
    parser.add_argument("--fuzz-seed", type=int, default=20260804)
    parser.add_argument("--corpus", default=None,
                        help="fuzz corpus dir (default tools/fuzz_corpus)")
    parser.add_argument("-q", "--quiet", action="store_true")
    ns = parser.parse_args(argv)

    package_dir = ns.package or os.path.dirname(os.path.dirname(_HERE))
    passes = ns.passes.split(",") if ns.passes else None
    if ns.passes == "none":
        passes = []  # fuzz-only / explicit no-op: don't re-run the statics
    elif passes:
        unknown = [p for p in passes if p not in PASS_NAMES]
        if unknown:
            print(f"rt-verify: unknown pass(es): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    if passes == []:
        # Fuzz-only mode: no static passes, and no allowlist application
        # (every entry would spuriously count as stale against zero
        # violations).
        violations, errors = [], []
    else:
        violations, errors = run_all(package_dir, passes=passes,
                                     allowlist_path=ns.allowlist)
    if not ns.quiet:
        for v in violations:
            print(v.render())
        for e in errors:
            print(f"ALLOWLIST ERROR: {e}")
    by_pass: Dict[str, int] = {}
    for v in violations:
        by_pass[v.pass_id] = by_pass.get(v.pass_id, 0) + 1
    detail = ", ".join(f"{k}={c}" for k, c in sorted(by_pass.items()))
    status = "FAILED" if (violations or errors) else "OK"
    print(f"rt-verify {status}: {len(violations)} violation(s)"
          + (f" ({detail})" if detail else "")
          + (f", {len(errors)} allowlist error(s)" if errors else ""))
    rc = 1 if (violations or errors) else 0

    if ns.fuzz > 0:
        from ray_tpu.devtools.verify import fuzz_wire

        try:
            fuzz_wire.run_fuzz(
                rounds=ns.fuzz, seed=ns.fuzz_seed,
                corpus_dir=ns.corpus or fuzz_wire.DEFAULT_CORPUS,
                quiet=ns.quiet,
            )
        except fuzz_wire.FuzzFailure as e:
            print(f"rt-verify FUZZ FAILED: {e}")
            return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
