"""Stale-binary guard: checked-in native binaries must match their source.

PR 7 committed built `.so`s next to their sources (fast cold start: no
compile on first import). Nothing detected drift: edit the .c, ship the old
.so, and every toolchain-less host silently runs the previous decoder. The
build flow now stamps each binary with the sha256 of the source it was
built from (`-D*_SRC_SHA256`, exported as a greppable
``RAY_TPU_*_SRC_SHA256=<hex>`` marker string); this pass re-hashes the
source and compares — pure file reads, no dlopen, no runtime import.

A missing binary is NOT a violation (they build on demand); a binary
without a stamp is (it predates the guard — rebuild it), and a stamp
mismatch is the exact failure this exists for.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

# The marker constants and the scan/hash helpers are the loader's own
# (ray_tpu._native defines the stamp format and self-heals on mismatch);
# importing them keeps the format in exactly ONE place. The import loads no
# .so — builds happen only inside load_arena_lib/load_wire_module.
from ray_tpu._native import (
    ARENA_HASH_MARKER, WIRE_HASH_MARKER, embedded_source_hash, source_sha256,
)
from ray_tpu.devtools.astutil import Violation, make_key
from ray_tpu.devtools.verify import DEFAULT_NATIVE_DIR

# binary -> (source, embedded marker prefix).
BINARIES: Dict[str, Tuple[str, bytes]] = {
    "wire_native.so": ("wire_native.c", WIRE_HASH_MARKER),
    "libshm_arena.so": ("shm_arena.cpp", ARENA_HASH_MARKER),
}


def run(pkg=None, native_dir: Optional[str] = None) -> List[Violation]:
    """`pkg` accepted (ignored) for pass-signature uniformity."""
    d = native_dir or DEFAULT_NATIVE_DIR
    violations: List[Violation] = []
    for so_name, (src_name, marker) in sorted(BINARIES.items()):
        so_path = os.path.join(d, so_name)
        src_path = os.path.join(d, src_name)
        if not os.path.exists(so_path) or not os.path.exists(src_path):
            continue  # binaries build on demand; nothing checked in to drift
        src_hash = source_sha256(src_path)
        got = embedded_source_hash(so_path, marker)
        if got is None:
            violations.append(Violation(
                "stale", so_path, 0,
                make_key("stale", so_path, "unstamped"),
                f"{so_name} carries no {marker.decode()!r} source stamp — "
                f"it predates the stale-binary guard; rebuild and recommit",
            ))
        elif got != src_hash:
            violations.append(Violation(
                "stale", so_path, 0,
                make_key("stale", so_path, "drift"),
                f"{so_name} was built from source {got[:12]}… but "
                f"{src_name} now hashes {src_hash[:12]}… — the checked-in "
                f"binary is stale; rebuild and recommit",
            ))
    return violations
