"""Structure-aware mutation fuzzer for the wire codecs.

Untrusted network bytes flow through two decoders that must agree: the C
extension (`_native/wire_native.c`) and its pure-Python twin
(`_private/wire._PyCodec`). This harness drives BOTH with mutated frames
and asserts, per case:

  - **typed rejection**: a malformed frame raises ValueError (the
    WireDecodeError family) — never struct.error, RecursionError,
    MemoryError, a segfault, or a silent half-decoded object;
  - **reject-parity**: the twins agree on accept-vs-reject, and on the
    decoded value when both accept (a frame one side accepts and the other
    rejects is a protocol fork between mixed-toolchain nodes);
  - **bounded work**: each decode completes within a wall-clock budget
    (hang/overallocation guard — the length-validation rules bound any
    allocation by the actual frame size).

Structure-aware: seeds are valid frames built from MESSAGE_GRAMMAR-shaped
messages; a pre-pass records the offset of every type byte and length field
in each seed, so mutations can surgically corrupt a length to 0xFFFFFFFF,
swap a type byte, truncate at a structural boundary, splice frames, or
build nesting bombs — the mutations that find decoder bugs, not just
checksum noise.

Seeded and replayable: the RNG seed prints with every failure, the failing
input is persisted to `<corpus>/crashers/<sha1>.bin` (named in the raised
error), and every file already in `<corpus>/seeds/`, `<corpus>/interesting/`
and `<corpus>/crashers/` is replayed FIRST on each run — fuzzer-found cases
become permanent regressions. Newly-seen rejection signatures are persisted
to `<corpus>/interesting/` (bounded), growing the corpus across runs.

This module intentionally imports the runtime codec (it is dynamic
verification, unlike the static passes). During fuzzing the codec HOOKS are
swapped for inert ones — decoding a mutated `H` frame must not feed
attacker-shaped bytes to pickle.loads or build half-valid dataclasses; the
real-hook hardening is covered by typed checks in wire._decode_hook and
tests/test_wire_fuzz.py.

Usage::

    python -m ray_tpu.devtools.verify --fuzz 12000 [--fuzz-seed N]
"""

from __future__ import annotations

import hashlib
import os
import struct
import time
from random import Random
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
DEFAULT_CORPUS = os.path.join(_REPO_ROOT, "tools", "fuzz_corpus")

_TYPE_BYTES = b"NTFifbsltdH"
_LEN_TYPES = b"bsltd"
_TIME_BUDGET_S = 1.0
# Global bound on <corpus>/interesting/ (existing files count toward it, so
# the corpus cannot grow without bound across runs). Must stay ABOVE the
# checked-in corpus size or growth is permanently disabled: ~330 shipped.
_MAX_INTERESTING = 512


class FuzzFailure(AssertionError):
    """A codec crash/hang/parity divergence, with the persisted input."""


# --------------------------------------------------------------------------
# Seed frames: grammar-shaped messages over simple values only (the hook
# escape is fuzzed at the byte level, not through live runtime dataclasses).
# --------------------------------------------------------------------------
def _simple_value(rng: Random, depth: int = 0):
    kinds = ["none", "bool", "int", "float", "bytes", "str"]
    if depth < 3:
        kinds += ["tuple", "list", "dict"]
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.choice([0, 1, -1, 255, -256, 2**31, -(2**31), 2**63 - 1, -(2**63)])
    if k == "float":
        return rng.choice([0.0, -0.0, 1.5, -2.75, 1e300, -1e-300])
    if k == "bytes":
        return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 48)))
    if k == "str":
        return "".join(rng.choice("abcé中 xyz_0") for _ in range(rng.randint(0, 24)))
    if k == "tuple":
        return tuple(_simple_value(rng, depth + 1) for _ in range(rng.randint(0, 4)))
    if k == "list":
        return [_simple_value(rng, depth + 1) for _ in range(rng.randint(0, 4))]
    return {
        rng.choice(["k", "kk", 7, b"b", True, None]): _simple_value(rng, depth + 1)
        for _ in range(rng.randint(0, 4))
    }


def make_seed_messages(rng: Random, grammar: Optional[dict] = None) -> List[tuple]:
    """Arity-correct simple-value messages for every grammar tag, plus a few
    deliberately gnarly shapes."""
    if grammar is None:
        from ray_tpu._private.protocol import MESSAGE_GRAMMAR as grammar
    out: List[tuple] = []
    for tag in sorted(grammar):
        lo, hi = grammar[tag]["arity"]
        n = rng.randint(lo, hi)
        out.append((tag,) + tuple(_simple_value(rng) for _ in range(n - 1)))
    out.append(("batch", [("cmd", "kv", _simple_value(rng)) for _ in range(4)]))
    out.append(("done", b"\x00" * 24, True, [], {"exec_start": 1.5}))
    out.append(("transfer_chunk", 2**40, 0, 65536))
    out.append(("cmd", "x" * 200, {"deep": [[["n"] * 8] * 4] * 2}))
    return out


# --------------------------------------------------------------------------
# Structural map of an encoded frame: (offset, type_byte) for every node,
# (offset,) for every u32 length field — recorded by a non-building parser
# so mutations hit real structure instead of random bytes.
# --------------------------------------------------------------------------
def frame_map(data: bytes) -> Tuple[List[int], List[int]]:
    type_offsets: List[int] = []
    len_offsets: List[int] = []

    def walk(pos: int, depth: int) -> int:
        if depth > 120 or pos >= len(data):
            raise ValueError("unmappable")
        t = data[pos:pos + 1]
        type_offsets.append(pos)
        pos += 1
        if t in (b"N", b"T", b"F"):
            return pos
        if t in (b"i", b"f"):
            return pos + 8
        if t in (b"b", b"s"):
            len_offsets.append(pos)
            (n,) = struct.unpack_from("<I", data, pos)
            return pos + 4 + n
        if t in (b"t", b"l"):
            len_offsets.append(pos)
            (n,) = struct.unpack_from("<I", data, pos)
            pos += 4
            for _ in range(n):
                pos = walk(pos, depth + 1)
            return pos
        if t == b"d":
            len_offsets.append(pos)
            (n,) = struct.unpack_from("<I", data, pos)
            pos += 4
            for _ in range(2 * n):
                pos = walk(pos, depth + 1)
            return pos
        if t == b"H":
            return walk(pos + 1, depth + 1)
        raise ValueError("unmappable")

    walk(0, 0)
    return type_offsets, len_offsets


# --------------------------------------------------------------------------
# Mutations
# --------------------------------------------------------------------------
def mutate(rng: Random, seed: bytes) -> bytes:
    try:
        type_offs, len_offs = frame_map(seed)
    except (ValueError, struct.error):
        type_offs, len_offs = [0], []
    buf = bytearray(seed)
    for _ in range(rng.randint(1, 3)):
        op = rng.randrange(8)
        if op == 0 and buf:  # truncate (structural boundary or anywhere)
            cut = rng.choice(type_offs) if rng.random() < 0.5 and type_offs \
                else rng.randrange(len(buf))
            del buf[cut:]
        elif op == 1 and buf:  # byte flips
            for _ in range(rng.randint(1, 4)):
                i = rng.randrange(len(buf))
                buf[i] ^= 1 << rng.randrange(8)
        elif op == 2 and len_offs:  # length-field corruption
            off = rng.choice(len_offs)
            if off + 4 <= len(buf):
                (n,) = struct.unpack_from("<I", bytes(buf), off)
                evil = rng.choice([0xFFFFFFFF, 0x7FFFFFFF, n + 1,
                                   max(0, n - 1), n * 1000 + 7, 0])
                struct.pack_into("<I", buf, off, evil & 0xFFFFFFFF)
        elif op == 3 and type_offs:  # type-byte swap
            off = rng.choice(type_offs)
            if off < len(buf):
                buf[off] = rng.choice(_TYPE_BYTES + b"\x00\xffZq")
        elif op == 4:  # nesting bomb
            depth = rng.choice([8, 64, 99, 100, 101, 150, 600])
            head = rng.choice([b"t", b"l"])
            buf = bytearray((head + struct.pack("<I", 1)) * depth + b"N")
        elif op == 5:  # hook frame
            buf = bytearray(b"H" + bytes([rng.randrange(256)]))
            buf += rng.choice([b"N", b"i" + b"\x01" * 8,
                               b"b" + struct.pack("<I", 4) + b"abcd",
                               b"t" + struct.pack("<I", 2) + b"NT"])
        elif op == 6 and buf:  # splice/duplicate a chunk
            i = rng.randrange(len(buf))
            j = rng.randrange(i, min(len(buf), i + 32) + 1)
            buf[i:i] = buf[i:j]
        else:  # append garbage
            buf += bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 8)))
    return bytes(buf)


# --------------------------------------------------------------------------
# Oracle
# --------------------------------------------------------------------------
def _norm(x):
    """Comparable normal form (repr handles nan, preserves dict order)."""
    return repr(x)


def _run_one(codec, data: bytes):
    """(outcome, detail): outcome 'ok'|'reject'; raises FuzzFailure on an
    untyped exception or a blown time budget."""
    t0 = time.monotonic()
    try:
        val = codec.unpack(data)
        outcome = ("ok", _norm(val))
    except ValueError as e:
        outcome = ("reject", f"{type(e).__name__}: {str(e)[:80]}")
    except Exception as e:  # noqa: BLE001 — the whole point of the harness
        raise FuzzFailure(
            f"untyped decode exception {type(e).__name__}: {e!r}"
        ) from e
    dt = time.monotonic() - t0
    if dt > _TIME_BUDGET_S:
        raise FuzzFailure(f"decode took {dt:.2f}s (budget {_TIME_BUDGET_S}s)")
    return outcome


class _InertHooks:
    """Hook pair for fuzzing: structural, deterministic, never unpickles."""

    @staticmethod
    def encode(obj):
        return None  # decline everything: seeds are simple values

    @staticmethod
    def decode(tag, payload):
        return ("__hook__", tag, payload)


class FuzzStats:
    def __init__(self) -> None:
        self.cases = 0        # total inputs checked (replay + seeds + mutations)
        self.replayed = 0     # corpus-replay inputs
        self.mutated = 0      # fresh mutation cases (the `rounds` budget)
        self.accepted = 0
        self.rejected = 0
        self.signatures: set = set()
        self.new_interesting = 0


def _persist(corpus_dir: str, sub: str, data: bytes, note: str = "") -> str:
    d = os.path.join(corpus_dir, sub)
    os.makedirs(d, exist_ok=True)
    name = hashlib.sha1(data).hexdigest()[:16]
    path = os.path.join(d, f"{name}.bin")
    if not os.path.exists(path):
        with open(path, "wb") as fh:
            fh.write(data)
        if note:
            with open(os.path.join(d, f"{name}.txt"), "w", encoding="utf-8") as fh:
                fh.write(note + "\n")
    return path


def _corpus_files(corpus_dir: str) -> List[str]:
    out: List[str] = []
    for sub in ("seeds", "interesting", "crashers"):
        d = os.path.join(corpus_dir, sub)
        if os.path.isdir(d):
            out.extend(
                os.path.join(d, f) for f in sorted(os.listdir(d))
                if f.endswith(".bin")
            )
    return out


def run_fuzz(rounds: int = 12000, seed: int = 20260804,
             corpus_dir: str = DEFAULT_CORPUS, persist: bool = True,
             quiet: bool = False, native_module=None) -> FuzzStats:
    """Fuzz both codecs with `rounds` cases each (corpus replay first).
    Raises FuzzFailure (crasher persisted + named) on any violation.
    `native_module` substitutes the C codec (the sanitizer stage passes an
    ASan/UBSan-built extension); default is the production build."""
    from ray_tpu import _native
    from ray_tpu._private import wire

    native = native_module if native_module is not None \
        else _native.load_wire_module()
    codecs = [("py", wire._PyCodec)]
    if native is not None:
        codecs.append(("c", native))
    elif not quiet:
        print("fuzz: C extension unavailable — fuzzing the Python codec only")

    # Swap in inert hooks (restored on exit) so mutated H frames stay safe.
    saved_py = (wire._encode_hook, wire._decode_hook)
    wire._encode_hook = _InertHooks.encode
    wire._decode_hook = _InertHooks.decode
    if native is not None:
        native.set_hooks(_InertHooks.encode, _InertHooks.decode)
    stats = FuzzStats()
    rng = Random(seed)
    try:
        def check(data: bytes, origin: str) -> None:
            stats.cases += 1
            outcomes = {}
            for cname, codec in codecs:
                try:
                    outcomes[cname] = _run_one(codec, data)
                except FuzzFailure as e:
                    path = _persist(corpus_dir, "crashers", data,
                                    f"{origin}: [{cname}] {e}") if persist else "<unpersisted>"
                    raise FuzzFailure(
                        f"[{cname}] {e} (origin {origin}, seed {seed}, "
                        f"input persisted at {path})"
                    ) from e
            # Parity is on accept-vs-reject and on accepted VALUES; reject
            # message text may legitimately differ between the twins.
            if len(outcomes) == 2 and (
                outcomes["py"][0] != outcomes["c"][0]
                or (outcomes["py"][0] == "ok" and outcomes["py"] != outcomes["c"])
            ):
                path = _persist(corpus_dir, "crashers", data,
                                f"{origin}: parity {outcomes}") if persist else "<unpersisted>"
                raise FuzzFailure(
                    f"reject-parity divergence py={outcomes['py']} "
                    f"c={outcomes['c']} (origin {origin}, seed {seed}, "
                    f"input persisted at {path})"
                )
            first = next(iter(outcomes.values()))
            if first[0] == "ok":
                stats.accepted += 1
            else:
                stats.rejected += 1
                sig = first[1]
                if sig not in stats.signatures:
                    stats.signatures.add(sig)
                    if persist and origin.startswith("mut") and \
                            interesting_on_disk + stats.new_interesting < _MAX_INTERESTING:
                        # A new rejection signature = new decoder path hit:
                        # keep the input so future runs replay it. The cap
                        # is GLOBAL (existing files count), so the corpus
                        # cannot grow without bound across runs.
                        if _persist(corpus_dir, "interesting", data, sig):
                            stats.new_interesting += 1

        interesting_dir = os.path.join(corpus_dir, "interesting")
        interesting_on_disk = (
            sum(1 for f in os.listdir(interesting_dir) if f.endswith(".bin"))
            if os.path.isdir(interesting_dir) else 0
        )

        # 1) corpus replay (seeds, prior interesting finds, prior crashers).
        for path in _corpus_files(corpus_dir):
            with open(path, "rb") as fh:
                check(fh.read(), f"corpus:{os.path.basename(path)}")
        stats.replayed = stats.cases

        # 2) seeded structure-aware mutation rounds. `rounds` budgets the
        # MUTATION cases alone — replay does not eat into it, so a growing
        # corpus can never silently erode fresh coverage.
        seeds = [wire._PyCodec.pack(m) for m in make_seed_messages(rng)]
        # Valid frames must round-trip both codecs before we mutate them.
        for i, s in enumerate(seeds):
            check(s, f"seed#{i}")
        while stats.mutated < rounds:
            check(mutate(rng, rng.choice(seeds)), f"mut#{stats.mutated}")
            stats.mutated += 1
    finally:
        wire._encode_hook, wire._decode_hook = saved_py
        if native is not None:
            native.set_hooks(*saved_py)
    if not quiet:
        per_codec = len(codecs)
        print(
            f"wire fuzz OK: {stats.cases} cases x {per_codec} codec(s) "
            f"({stats.replayed} corpus-replay + {stats.mutated} mutations), "
            f"{stats.accepted} accepted / {stats.rejected} rejected, "
            f"{len(stats.signatures)} distinct reject signatures "
            f"({stats.new_interesting} new persisted), seed {seed}"
        )
    return stats


def write_seed_corpus(corpus_dir: str = DEFAULT_CORPUS, seed: int = 1) -> int:
    """Materialize the canonical seed frames under <corpus>/seeds/ (checked
    in once; replayed at the start of every run)."""
    from ray_tpu._private import wire

    rng = Random(seed)
    n = 0
    for msg in make_seed_messages(rng):
        _persist(corpus_dir, "seeds", wire._PyCodec.pack(msg))
        n += 1
    return n
