"""Lock-order pass: acquisition-graph cycle detection over the control
plane's locks.

PRs 6-7 put four independently-locked components on the same hot paths
(PullManager, PushManager, OwnershipTable, BatchedSender — plus the
scheduler's send/wake locks and the module-level locate registry). A
deadlock needs two threads taking two of those locks in opposite orders;
no per-site lint can see it, but the ACQUISITION GRAPH can: every
`with <lock>:` body (and every `@lock_guarded` method, whose whole body
runs under its named lock) contributes held->acquired edges, calls inside
a held region contribute edges to every lock the callee may transitively
acquire, and any cycle in the resulting graph is a potential deadlock.

Lock identity is static and class-scoped (`PullManager._lock`,
`BatchedSender._lock`, `object_transfer._locate_lock`): two instances of
one class share a node, so a self-edge means "holds an instance's lock
while taking the same lock of a (possibly different) instance" — the
same-instance case is an instant deadlock with plain Locks, the
cross-instance case is an ordering hazard; both deserve a look, and a
justified allowlist entry if deliberate.

Resolution (same under-approximation contract as the blocking pass):
`self.X` locks bind to the enclosing class; `alias.X` follows one local
`alias = self.attr` hop through the class's attr-type map (built from
`self.attr = ClassName(...)` assignments and annotated __init__ params);
module-level `with _lock:` binds to the module; anything else becomes an
`?.X` node (kept distinct by attribute name, never merged with a resolved
class). Calls resolve like the blocking pass: self-methods, local/imported
functions, then unique bare names.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.astutil import (
    FuncInfo, Package, Violation, call_name, dotted, imported_names, make_key,
    walk_body,
)

DEFAULT_GRAPH_MODULES = (
    "ray_tpu._private.scheduler",
    "ray_tpu._private.batching",
    "ray_tpu._private.object_transfer",
    "ray_tpu._private.ownership",
    "ray_tpu._private.object_store",
    "ray_tpu._private.worker",
    "ray_tpu._private.worker_main",
    "ray_tpu._private.node_daemon",
    "ray_tpu._private.gcs",
    "ray_tpu._private.telemetry",
    "ray_tpu._private.session_monitor",
    "ray_tpu._private.failpoints",
    "ray_tpu._private.tracing_runtime",
    "ray_tpu.util.metrics",
)

# Bare names too generic for unique-name call resolution.
_SKIP_RESOLVE = {
    "get", "put", "pop", "append", "add", "remove", "send", "close", "items",
    "values", "keys", "update", "clear", "copy", "extend", "set", "start",
    "stop", "run", "join", "wait", "result", "acquire", "release", "submit",
    "flush", "note", "read", "write",
}


def _is_lockish(name: str) -> bool:
    return "lock" in name.lower()


class _Analysis:
    def __init__(self, pkg: Package, modules: Set[str]) -> None:
        self.pkg = pkg
        self.infos = [f for f in pkg.functions.values() if f.module in modules]
        self.by_key = {f.key: f for f in self.infos}
        by_name: Dict[str, List[FuncInfo]] = {}
        for f in self.infos:
            by_name.setdefault(f.name, []).append(f)
        self.by_name = by_name
        self.imports = {
            m: imported_names(tree)
            for m, tree in pkg.modules.items() if m in modules
        }
        self.class_names = {f.cls for f in self.infos if f.cls}
        self.module_locks: Dict[str, Set[str]] = {}
        for m in modules:
            tree = pkg.modules.get(m)
            if tree is None:
                continue
            locks: Set[str] = set()
            for node in tree.body:
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    _, meth = call_name(node.value)
                    if meth in ("Lock", "RLock", "Condition"):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                locks.add(tgt.id)
            self.module_locks[m] = locks
        # (module, class) -> {attr: ClassName} from `self.attr = ClassName(...)`
        # and annotated __init__ params assigned to self.attr.
        self.attr_types: Dict[Tuple[str, str], Dict[str, str]] = {}
        for f in self.infos:
            if not f.cls:
                continue
            amap = self.attr_types.setdefault((f.module, f.cls), {})
            ann: Dict[str, str] = {}
            args = getattr(f.node, "args", None)
            if args is not None:
                for a in list(args.args) + list(args.kwonlyargs):
                    t = a.annotation
                    if isinstance(t, ast.Name):
                        ann[a.arg] = t.id
                    elif isinstance(t, ast.Constant) and isinstance(t.value, str):
                        ann[a.arg] = t.value.strip('"')
                    elif isinstance(t, ast.Attribute):
                        ann[a.arg] = t.attr
            for node in walk_body(f.node):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        v = node.value
                        if isinstance(v, ast.Call):
                            _, ctor = call_name(v)
                            if ctor in self.class_names:
                                amap.setdefault(tgt.attr, ctor)
                        elif isinstance(v, ast.Name) and v.id in ann:
                            amap.setdefault(tgt.attr, ann[v.id])

    # ------------------------------------------------------------ lock ids
    def lock_id(self, expr: ast.AST, info: FuncInfo,
                local_aliases: Dict[str, str]) -> Optional[str]:
        d = dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        leaf = parts[-1]
        if not _is_lockish(leaf):
            return None
        if len(parts) == 1:
            if leaf in self.module_locks.get(info.module, ()):
                return f"{info.module.rsplit('.', 1)[-1]}.{leaf}"
            return f"?.{leaf}"
        owner = parts[0]
        if owner == "self" and info.cls:
            if len(parts) == 2:
                return f"{info.cls}.{leaf}"
            # self.attr._lock: resolve attr's class if known.
            t = self.attr_types.get((info.module, info.cls), {}).get(parts[1])
            return f"{t or '?' + parts[1]}.{leaf}"
        # alias.X where alias = self.attr earlier in this function.
        src_attr = local_aliases.get(owner)
        if src_attr is not None and info.cls:
            t = self.attr_types.get((info.module, info.cls), {}).get(src_attr)
            return f"{t or '?' + src_attr}.{leaf}"
        return f"?{owner}.{leaf}"

    def local_aliases(self, info: FuncInfo) -> Dict[str, str]:
        """name -> self-attr for `x = self.attr` assignments in the body."""
        out: Dict[str, str] = {}
        for node in walk_body(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                v = node.value
                if isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name) and v.value.id == "self":
                    out[node.targets[0].id] = v.attr
        return out

    # ------------------------------------------------------- call resolution
    def callees(self, info: FuncInfo, node: ast.Call) -> List[FuncInfo]:
        recv, meth = call_name(node)
        if not meth:
            return []
        if recv == "self" and info.cls:
            got = self.by_key.get(f"{info.module}:{info.cls}.{meth}")
            return [got] if got else []
        if recv is None:
            got = self.by_key.get(f"{info.module}:{meth}")
            if got:
                return [got]
            src = self.imports.get(info.module, {}).get(meth)
            if src:
                mod, _, name = src.rpartition(".")
                got = self.by_key.get(f"{mod}:{name}")
                if got:
                    return [got]
        if meth in _SKIP_RESOLVE:
            return []
        cands = self.by_name.get(meth, ())
        return list(cands) if len(cands) == 1 else []

    # ----------------------------------------------------- per-function data
    def guard_locks(self, info: FuncInfo) -> Set[str]:
        """Locks this function requires held at ENTRY (@lock_guarded)."""
        out: Set[str] = set()
        for dec in info.node.decorator_list:
            if isinstance(dec, ast.Call):
                _, name = call_name(dec)
                if name == "lock_guarded" and dec.args:
                    arg = dec.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                            and info.cls:
                        out.add(f"{info.cls}.{arg.value}")
        return out

    def direct_acquisitions(self, info: FuncInfo) -> List[Tuple[str, ast.With]]:
        out: List[Tuple[str, ast.With]] = []
        aliases = self.local_aliases(info)
        for node in walk_body(info.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self.lock_id(item.context_expr, info, aliases)
                    if lid is not None:
                        out.append((lid, node))
        return out


def _walk_no_defs(root: ast.AST):
    """Walk below `root` without descending into nested defs/lambdas."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _acq_fixpoint(an: _Analysis) -> Dict[str, Set[str]]:
    """key -> every lock the function may (transitively) acquire."""
    acq: Dict[str, Set[str]] = {}
    calls: Dict[str, Set[str]] = {}
    for f in an.infos:
        acq[f.key] = {lid for lid, _ in an.direct_acquisitions(f)}
        callee_keys: Set[str] = set()
        for node in walk_body(f.node):
            if isinstance(node, ast.Call):
                callee_keys.update(c.key for c in an.callees(f, node))
        calls[f.key] = callee_keys
    changed = True
    while changed:
        changed = False
        for key, callee_keys in calls.items():
            cur = acq[key]
            for ck in callee_keys:
                extra = acq.get(ck, ())
                for lid in extra:
                    if lid not in cur:
                        cur.add(lid)
                        changed = True
    return acq


def run(pkg: Package, graph_modules=DEFAULT_GRAPH_MODULES) -> List[Violation]:
    modules = {m for m in graph_modules if m in pkg.modules}
    # Fixture packages use bare module names: fall back to "everything".
    if not modules:
        modules = set(pkg.modules)
    an = _Analysis(pkg, modules)
    acq = _acq_fixpoint(an)

    # held -> acquired edges, with one sample site each.
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(held: str, taken: str, info: FuncInfo, line: int,
                 why: str) -> None:
        if held == taken:
            # Self-edge: report directly (cycle detection would hide which
            # site) — same-instance re-acquisition deadlocks a plain Lock.
            key = make_key("lockorder", info.path, info.qualname,
                           f"self-cycle={taken}")
            if key not in _self_seen:
                _self_seen[key] = Violation(
                    "lockorder", info.path, line, key,
                    f"{info.qualname} may acquire {taken} while already "
                    f"holding it ({why}) — deadlock if both are the same "
                    f"instance, ordering hazard otherwise",
                )
            return
        edges.setdefault((held, taken), (info.path, line, info.qualname))

    _self_seen: Dict[str, Violation] = {}

    for f in an.infos:
        held_at_entry = an.guard_locks(f)
        directs = an.direct_acquisitions(f)
        # Entry-held locks cover the whole body.
        for held in held_at_entry:
            for lid, wnode in directs:
                add_edge(held, lid, f, wnode.lineno, "@lock_guarded entry")
            for node in walk_body(f.node):
                if isinstance(node, ast.Call):
                    for callee in an.callees(f, node):
                        for lid in acq.get(callee.key, ()):
                            add_edge(held, lid, f, node.lineno,
                                     f"calls {callee.qualname}")
        # With-block regions. Nested defs/lambdas are excluded: code inside
        # them runs when CALLED (often on another thread, after the with
        # exits), not while this lock is held.
        aliases = an.local_aliases(f)
        for lid, wnode in directs:
            for inner in _walk_no_defs(wnode):
                if isinstance(inner, ast.With):
                    for item in inner.items:
                        ilid = an.lock_id(item.context_expr, f, aliases)
                        if ilid is not None:
                            add_edge(lid, ilid, f, inner.lineno, "nested with")
                elif isinstance(inner, ast.Call):
                    for callee in an.callees(f, inner):
                        for ilid in acq.get(callee.key, ()):
                            add_edge(lid, ilid, f, inner.lineno,
                                     f"calls {callee.qualname}")

    violations: List[Violation] = list(_self_seen.values())

    # Cycle detection over the edge graph (DFS with stack coloring).
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []
    cycles: List[List[str]] = []

    def dfs(n: str) -> None:
        color[n] = GREY
        stack.append(n)
        for nxt in sorted(graph[n]):
            if color[nxt] == GREY:
                cyc = stack[stack.index(nxt):] + [nxt]
                cycles.append(cyc)
            elif color[nxt] == WHITE:
                dfs(nxt)
        stack.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color[n] == WHITE:
            dfs(n)

    seen_cycles: Set[frozenset] = set()
    for cyc in cycles:
        ident = frozenset(cyc)
        if ident in seen_cycles:
            continue
        seen_cycles.add(ident)
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            path, line, qual = edges[(a, b)]
            sites.append(f"{a}->{b} at {os.path.basename(path)}:{line} ({qual})")
        violations.append(Violation(
            "lockorder", "lock-graph", 0,
            make_key("lockorder", "lock-graph",
                     "cycle=" + ">".join(sorted(set(cyc)))),
            "lock-order cycle: " + "; ".join(sites),
        ))
    return violations
