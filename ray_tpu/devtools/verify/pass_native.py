"""Native-source pass: stdlib static checks over the C/C++ extensions.

The wire decoder and the shm arena are the only code in the tree where a
missed check is a segfault or a silent heap corruption instead of a
traceback, and (unlike the Python tree) no interpreter-level tooling sees
them. This pass parses `_native/*.c` / `*.cpp` with a comment/string-
stripping brace scanner — no compiler needed — and checks three properties:

  C1 unchecked-alloc   the result of PyMem_Malloc / PyMem_Realloc / malloc
                       is used without a null check anywhere in the function
  C2 unchecked-length  memcpy/memmove/memset with a VARIABLE length operand
                       in a function that never validates that variable
                       (no bounds `if`, no r_need/w_reserve-style checker
                       call mentioning it) — the length-field-before-memcpy
                       class of decoder bug
  C3 leak-on-error     an error return (`return NULL` / `return -1`) while
                       a Python object acquired earlier in the function
                       (PyTuple_New, PyBytes_FromStringAndSize, hook call
                       results, ...) is still owned and never released on
                       any path (`Py_DECREF`/`Py_XDECREF`/`Py_XSETREF`,
                       `return var`, or a stealing SET_ITEM)

Heuristic by design (C3 is flow-insensitive per variable: one release
anywhere ends tracking), so occasional false positives go to the verify
allowlist with a justification — same contract as every rt-lint pass.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from ray_tpu.devtools.astutil import Violation, make_key

# Functions returning a NEW Python reference the caller owns.
_NEW_REF_FNS = (
    "PyTuple_New", "PyList_New", "PyDict_New", "_PyDict_NewPresized",
    "PyBytes_FromStringAndSize", "PyUnicode_DecodeUTF8",
    "PyUnicode_FromString", "PyLong_FromLongLong", "PyLong_FromLong",
    "PyFloat_FromDouble", "PyObject_CallFunctionObjArgs",
    "PyObject_CallObject", "PyModule_Create", "decode_obj",
)
_ALLOC_FNS = ("PyMem_Malloc", "PyMem_Realloc", "malloc", "realloc", "calloc")
_RELEASE_RE = r"Py_DECREF|Py_XDECREF|Py_XSETREF|Py_SETREF"
# Calls that transfer ownership of their argument (stolen reference).
_STEAL_FNS = ("PyTuple_SET_ITEM", "PyList_SET_ITEM", "PyModule_AddObject")
# Checker helpers whose call constitutes a bounds validation of an operand.
_BOUND_CHECK_FNS = ("r_need", "w_reserve", "w_u32", "r_u32")

from ray_tpu.devtools.verify import DEFAULT_NATIVE_DIR  # noqa: E402


def strip_comments_and_strings(src: str) -> str:
    """Blank out comments, string and char literals (newlines preserved so
    line numbers survive)."""
    out: List[str] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("".join(ch if ch == "\n" else " " for ch in src[i:j]))
            i = j
        elif c in "\"'":
            q = c
            j = i + 1
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src[j] == q:
                    j += 1
                    break
                j += 1
            out.append(q + " " * (j - i - 2) + (q if j <= n else ""))
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


_FUNC_NAME_RE = re.compile(r"(\w+)\s*\([^()]*(?:\([^()]*\)[^()]*)*\)\s*(?:const\s*)?$")


def extract_functions(src: str) -> List[Tuple[str, int, str]]:
    """[(name, start_line, body)] for every top-level function definition;
    descends into `namespace {...}` / `extern "C" {...}` blocks."""
    clean = strip_comments_and_strings(src)
    funcs: List[Tuple[str, int, str]] = []

    def scan(text: str, base_line: int) -> None:
        depth = 0
        seg_start = 0  # start of the current "header" segment
        i = 0
        n = len(text)
        while i < n:
            c = text[i]
            if c in ";}":
                if depth == 0:
                    seg_start = i + 1
            elif c == "{":
                if depth == 0:
                    header = text[seg_start:i].strip()
                    # find the matching close brace
                    d = 1
                    j = i + 1
                    while j < n and d:
                        if text[j] == "{":
                            d += 1
                        elif text[j] == "}":
                            d -= 1
                        j += 1
                    body = text[i + 1:j - 1]
                    line = base_line + text[:i].count("\n")
                    if ("namespace" in header or "extern" in header) and \
                            "(" not in header:
                        scan_inner_base = base_line + text[:i + 1].count("\n")
                        scan(body, scan_inner_base)
                    else:
                        m = _FUNC_NAME_RE.search(header)
                        if m and not header.rstrip().endswith("=") and \
                                not re.search(r"\b(struct|class|enum|union)\s+\w+$",
                                              header):
                            funcs.append((m.group(1), line, body))
                    seg_start = j
                    i = j
                    continue
                depth += 1
            i += 1

    scan(clean, 1)
    return funcs


_ASSIGN_RE = re.compile(
    r"((?:\w+(?:->|\.))*\w+)\s*=\s*(?:\([^)]*\)\s*)?(\w+)\s*\("
)
_RETURN_ERR_RE = re.compile(r"\breturn\s+(NULL|nullptr|-\s*\w+|-?\d+)\s*;")
_RETURN_VAR_RE = re.compile(r"\breturn\s+(\w+)\s*;")


def _statements(body: str):
    """Yield (offset, stmt) roughly per ';'/'{'/'}' boundary."""
    start = 0
    for i, c in enumerate(body):
        if c in ";{}":
            stmt = body[start:i + 1]
            if stmt.strip():
                yield start, stmt
            start = i + 1


def check_function(path: str, name: str, start_line: int, body: str
                   ) -> List[Violation]:
    violations: List[Violation] = []
    base = os.path.basename(path)

    def line_of(off: int) -> int:
        return start_line + body[:off].count("\n")

    # --- C1: unchecked allocations -------------------------------------
    for m in _ASSIGN_RE.finditer(body):
        var, fn = m.group(1), m.group(2)
        if fn not in _ALLOC_FNS:
            continue
        checked = re.search(
            rf"!\s*{re.escape(var)}\b|\b{re.escape(var)}\s*==\s*(NULL|nullptr|0)\b"
            rf"|\b(NULL|nullptr)\s*==\s*{re.escape(var)}\b",
            body,
        )
        if not checked:
            violations.append(Violation(
                "native", path, line_of(m.start()),
                make_key("native", base, name, f"alloc={var}", "unchecked"),
                f"{name}: result of {fn}() assigned to {var!r} is never "
                f"null-checked in this function",
            ))

    # --- C2: variable-length memcpy without a bounds check --------------
    for m in re.finditer(r"\b(memcpy|memmove|memset)\s*\(", body):
        # crude argument split of the top-level call
        j = m.end()
        d = 1
        while j < len(body) and d:
            if body[j] == "(":
                d += 1
            elif body[j] == ")":
                d -= 1
            j += 1
        args = body[m.end():j - 1]
        parts, cur, d2 = [], "", 0
        for ch in args:
            if ch == "," and d2 == 0:
                parts.append(cur)
                cur = ""
                continue
            if ch in "([":
                d2 += 1
            elif ch in ")]":
                d2 -= 1
            cur += ch
        parts.append(cur)
        if len(parts) < 3:
            continue
        length = parts[-1].strip()
        if re.fullmatch(r"\d+|sizeof\s*\(.*\)", length):
            continue  # constant length: fine
        lvars = set(re.findall(r"\b([a-zA-Z_]\w*)\b", length)) - {
            "sizeof", "uint32_t", "uint64_t", "int64_t", "size_t", "Py_ssize_t",
        }
        ok = False
        prefix = body[:m.start()]
        for v in lvars:
            if re.search(rf"\b({'|'.join(_BOUND_CHECK_FNS)})\s*\([^;]*\b{re.escape(v)}\b", prefix) or \
                    re.search(rf"\bif\s*\([^)]*\b{re.escape(v)}\b[^)]*[<>]", prefix) or \
                    re.search(rf"\b{re.escape(v)}\s*=\s*[^;]*\b({'|'.join(_BOUND_CHECK_FNS)})", prefix):
                ok = True
        if lvars and not ok:
            violations.append(Violation(
                "native", path, line_of(m.start()),
                make_key("native", base, name, f"len={'/'.join(sorted(lvars))}", "memcpy"),
                f"{name}: {m.group(1)} length {length!r} is never bounds-"
                f"checked before the copy in this function",
            ))

    # --- C3: owned references leaked on error returns -------------------
    # Position-aware: at each `return NULL`/`return -1`, every object
    # acquired BEFORE it must have some release (DECREF / return var /
    # stealing SET_ITEM) at an EARLIER offset — "the success path returns
    # it at the end" does not excuse an early error exit. One release
    # exempts all later returns (conservative: correct error ladders
    # DECREF in their first error block).
    acquired: Dict[str, int] = {}
    first_release: Dict[str, int] = {}
    for m in _ASSIGN_RE.finditer(body):
        var, fn = m.group(1), m.group(2)
        if fn in _NEW_REF_FNS and var not in acquired:
            acquired[var] = m.start()
            pat = (
                rf"(?:{_RELEASE_RE})\s*\(\s*{re.escape(var)}\b"
                rf"|\breturn\s+{re.escape(var)}\s*;"
                rf"|\b(?:{'|'.join(_STEAL_FNS)})\s*\([^;]*\b{re.escape(var)}\s*\)"
            )
            rm = re.search(pat, body)
            if rm:
                first_release[var] = rm.start()
    if acquired:
        for off, stmt in _statements(body):
            rm = _RETURN_ERR_RE.search(stmt)
            if not rm:
                continue
            ret_off = off + rm.start()
            # The enclosing `if (...)` condition (if adjacent): a
            # `if (!var) return NULL;` is the var's OWN failure check.
            cond_m = None
            for cm in re.finditer(r"if\s*\(([^)]*(?:\([^)]*\)[^)]*)*)\)\s*(?:\{[^{}]*)?$",
                                  body[:ret_off]):
                cond_m = cm
            cond = cond_m.group(1) if cond_m and \
                ret_off - cond_m.end() < 200 else ""
            for var, acq_off in acquired.items():
                if acq_off >= ret_off:
                    continue  # acquired after this return
                if first_release.get(var, len(body) + 1) < ret_off:
                    continue  # released on some earlier path
                if re.search(rf"(?<![\w>]){re.escape(var)}\b", cond) and (
                        f"!{var}" in cond.replace(" ", "")
                        or re.search(rf"{re.escape(var)}\s*==\s*(NULL|nullptr|0)", cond)):
                    continue  # this return IS var's null-check
                violations.append(Violation(
                    "native", path, line_of(ret_off),
                    make_key("native", base, name, f"leak={var}",
                             f"ret@{line_of(ret_off)}"),
                    f"{name}: error return leaks owned reference {var!r} "
                    f"(acquired at line {line_of(acq_off)}, not released "
                    f"before this exit)",
                ))
    return violations


def run(pkg=None, native_dir: Optional[str] = None,
        sources: Optional[Dict[str, str]] = None) -> List[Violation]:
    """`pkg` is accepted (and ignored) for pass-signature uniformity."""
    violations: List[Violation] = []
    if sources is None:
        sources = {}
        d = native_dir or DEFAULT_NATIVE_DIR
        if os.path.isdir(d):
            for fname in sorted(os.listdir(d)):
                if fname.endswith((".c", ".cc", ".cpp")):
                    fpath = os.path.join(d, fname)
                    with open(fpath, "r", encoding="utf-8") as fh:
                        sources[fpath] = fh.read()
    for path, src in sources.items():
        for name, line, body in extract_functions(src):
            violations.extend(check_function(path, name, line, body))
    return violations
