"""rt-state side B: systematic interleaving exploration of the control plane.

The static pass (`devtools/pass_lifecycle.py`) proves every state WRITE is a
declared transition; this module explores whether legal-looking handler code
reaches illegal states under reordered delivery and crashes. It runs the REAL
`Scheduler` handler methods single-threaded against a virtual harness:

  * `Scheduler(virtual=True)` builds the full in-memory control plane but
    binds no listeners and is never `start()`ed. The harness claims the loop
    thread (`_loop_tid`) so every `@loop_thread_only` handler runs inline.
  * The batched-send seam (`_send_to` -> `_flush_outbound` ->
    `conn.send_bytes`) is intercepted by `VirtualConn`: outbound frames are
    decoded and fed to small peer models (worker / daemon) whose replies
    become *pending delivery events* instead of being applied immediately.
  * The explorer then permutes the schedule: per-peer FIFO delivery queues
    (channel order is preserved, cross-channel order is not) plus global
    events (worker crash, heartbeat verdict, drain-deadline sweep). Each
    schedule re-executes the scenario from scratch (stateless model
    checking), so any prefix of event keys replays deterministically.
  * Exploration is a bounded DFS with a sleep-set partial-order reduction:
    deliveries from distinct peers are treated as independent (they commute
    up to bookkeeping our invariants do not observe), so only one order per
    such pair is explored; anything involving a global event or a shared
    FIFO is explored in every order. The reduction is a heuristic static
    independence relation, not a proof — the planted-bug tests in
    `tests/test_explore.py` pin that the orders that matter stay explored.

Checked after every delivery and at quiescence:
  * lifecycle legality — `_private/lifecycle.py` runtime monitor armed; an
    undeclared transition raises inside the handler and fails the schedule.
  * no lost task — every submitted task reaches a terminal state once no
    events remain (a PENDING/RUNNING task at quiescence can never finish).
  * no double seal — at most one non-error seal per object id.
  * eventual quiescence — every schedule drains within a step budget.

Scenario families (`SCENARIOS`): submit-vs-worker-death (lease-pipelined
tasks racing a worker crash and a SUSPECT verdict), seal-vs-owner-death (a
worker-submitted child task racing its owner's crash), heartbeat-verdict-vs-
rejoin (staleness detector racing a late daemon heartbeat), drain-vs-kill
(graceful serve drain racing the target's death and the deadline sweep).

Interesting schedules persist under `tools/explore_corpus/` (one JSON per
scenario, like `tools/fuzz_corpus/`): `run_sweep` replays the stored corpus
first, then explores fresh. Schedules are plain event-key lists, so a corpus
entry reproduces across processes: `replay(scenario, schedule)`.
"""

from __future__ import annotations

import json
import os
import random
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private import lifecycle, serialization
from ray_tpu._private.config import Config
from ray_tpu._private.gcs import GCS
from ray_tpu._private.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import ObjectMeta
from ray_tpu._private.protocol import ExecRequest, FunctionDescriptor, TaskSpec
from ray_tpu._private.scheduler import (
    ActorRecord,
    DaemonHandle,
    Scheduler,
    WorkerHandle,
    fast_task_record,
)

DEFAULT_SEED = 20260807
DEFAULT_BUDGET = 400
MAX_STEPS = 64

# __file__ = <root>/ray_tpu/devtools/verify/explore.py -> <root>/tools/...
CORPUS_DIR = os.path.join(
    os.path.dirname(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    ),
    "tools",
    "explore_corpus",
)


# --------------------------------------------------------------------- virtual pieces
class _VirtualProc:
    """Quacks like _Proc for a worker that exists only in the harness."""

    pid = -1

    def __init__(self):
        self._alive = True

    def is_alive(self) -> bool:
        return self._alive

    def terminate(self) -> None:
        self._alive = False

    def join(self, timeout: Optional[float] = None) -> None:
        pass

    def mark_dead(self) -> None:
        self._alive = False


class VirtualConn:
    """The send-seam intercept. `send_bytes` decodes the frame and hands it
    to the harness peer model synchronously; the model only ENQUEUES reply
    events (it never calls back into the scheduler), so handler re-entrancy
    cannot occur. After `close()` sends raise OSError, which drives the
    scheduler's real send-failure -> death path."""

    def __init__(self, harness: "Harness", peer: str):
        self.harness = harness
        self.peer = peer
        self.closed = False

    def fileno(self) -> int:
        return -1  # selector registration fails -> swallowed by _watch_conn

    def send_bytes(self, data: bytes) -> None:
        if self.closed:
            raise OSError(f"virtual conn to {self.peer} closed")
        self.harness._on_frame(self.peer, serialization.loads(data))

    def poll(self, *_a) -> bool:
        return False

    def recv_bytes(self) -> bytes:
        raise EOFError

    def close(self) -> None:
        self.closed = True


class VirtualScheduler(Scheduler):
    """Scheduler(virtual=True) + deterministic worker spawning through the
    harness + seal accounting for the no-double-seal invariant. Planted-bug
    fixtures subclass THIS (see tests/test_explore.py) and are passed to
    explore(sched_cls=...)."""

    harness: Optional["Harness"] = None

    def _spawn_worker(self, node, actor_id=None, env_vars=None,
                      runtime_env=None) -> WorkerHandle:
        h = self.harness
        h.spawn_seq += 1
        from ray_tpu._private.runtime_env import env_hash as _renv_hash

        worker_id = WorkerID(h.spawn_seq.to_bytes(WorkerID.SIZE, "little"))
        wh = WorkerHandle(
            worker_id=worker_id,
            node_id=node.node_id,
            process=_VirtualProc(),
            state="idle" if actor_id is None else "busy",
            actor_id=actor_id,
            env_hash=_renv_hash(runtime_env),
        )
        node.workers[worker_id] = wh
        self._workers_by_id[worker_id.hex()] = wh
        if actor_id is None:
            node.idle.append(worker_id)
        h.register_worker(wh)
        return wh

    def _seal_object(self, meta: ObjectMeta):
        h = self.harness
        if h is not None and not meta.is_error:
            key = meta.object_id.binary()
            h.seal_counts[key] = h.seal_counts.get(key, 0) + 1
        return super()._seal_object(meta)


# --------------------------------------------------------------------- harness
class Harness:
    """One virtual cluster for one schedule execution. Owns the event
    queues; `fire(key)` applies one event through the real handlers and then
    runs a scheduling pass + outbound flush, exactly like one loop tick."""

    def __init__(self, sched_cls=VirtualScheduler):
        cfg = Config()
        cfg.enable_metrics = False
        cfg.enable_obs = False
        cfg.memory_monitor_refresh_ms = 0
        cfg.log_to_driver = False
        self.sched = sched_cls(
            GCS(), cfg, session_dir="/nonexistent/rt-explore", virtual=True
        )
        self.sched.harness = self
        self.sched._loop_tid = threading.get_ident()
        self.spawn_seq = 0
        self.workers: Dict[str, WorkerHandle] = {}
        self.conns: Dict[str, VirtualConn] = {}
        # Per-peer FIFO of (event_key, thunk): only the head is deliverable.
        self.channels: Dict[str, deque] = {}
        # Global one-shot events (crash / verdict / sweep), armed by scenarios.
        self.globals_: Dict[str, Callable[[], None]] = {}
        self.crashed: set = set()
        self.seal_counts: Dict[bytes, int] = {}
        self.violations: List[str] = []
        # Per-task exec hooks: first byte of task id -> hook(h, peer, req),
        # run before the default done reply is queued (scenario scaffolding).
        self.exec_hooks: Dict[int, Callable] = {}
        # Virtual clock for the heartbeat scenarios (seconds since setup).
        self.vclock = 0.0
        self._prev_lifecycle_enabled = lifecycle.ENABLED
        lifecycle.reset()
        lifecycle.ENABLED = True

    # -- lifecycle of the harness itself
    def close(self) -> None:
        lifecycle.ENABLED = self._prev_lifecycle_enabled
        lifecycle.reset()
        s = self.sched
        for sock in (s._wake_r, s._wake_w, s._urgent_r, s._urgent_w):
            try:
                sock.close()
            except OSError:
                pass
        try:
            s._selector.close()
        except OSError:
            pass

    # -- cluster construction helpers (scenario scaffolding)
    def add_node(self, resources: Dict[str, float]) -> NodeID:
        return self.sched._cmd_add_node((resources, {}))

    def add_daemon_node(self, resources: Dict[str, float]):
        nid = self.add_node(resources)
        node = self.sched.nodes[nid]
        name = "d%d" % (len(self.conns) + 1)
        conn = VirtualConn(self, name)
        self.conns[name] = conn
        daemon = DaemonHandle(nid, conn)
        node.daemon = daemon
        self.sched._conn_to_daemon[conn] = daemon
        return nid, daemon

    def make_record(self, idx: int, max_retries: int = 0,
                    resources: Optional[Dict[str, float]] = None):
        tid = TaskID(bytes([idx]) * TaskID.SIZE)
        spec = TaskSpec(
            task_id=tid,
            func=FunctionDescriptor("fid", "f"),
            resources={"CPU": 1.0} if resources is None else resources,
            max_retries=max_retries,
        )
        return fast_task_record(
            spec, (), {}, [ObjectID.for_return(tid, 1)], b"blob", max_retries
        )

    def submit(self, idx: int, max_retries: int = 0) -> None:
        self.sched._cmd_submit(self.make_record(idx, max_retries))

    def register_worker(self, wh: WorkerHandle) -> None:
        name = "w%d" % self.spawn_seq
        conn = VirtualConn(self, name)
        self.workers[name] = wh
        self.conns[name] = conn
        self.channels.setdefault(name, deque())
        wh.attach(conn)
        self.sched._conn_to_worker[conn] = wh
        self.sched._watch_conn(conn)

    # -- peer models: decode outbound frames, enqueue reply events
    def _on_frame(self, peer: str, msg) -> None:
        if msg[0] == "batch":
            for m in msg[1]:
                self._on_frame(peer, m)
            return
        if peer not in self.workers:
            return  # daemon model: ignores shutdown/stacks/etc.
        kind = msg[0]
        if kind == "exec":
            req: ExecRequest = msg[1]
            tid = req.spec.task_id
            hook = self.exec_hooks.get(tid.binary()[0])
            if hook is not None:
                hook(self, peer, req)
            payload = b"result:" + tid.hex().encode()
            metas = [
                ObjectMeta(object_id=oid, size=len(payload), inband=payload)
                for oid in req.return_ids
            ]
            self.queue_delivery(
                peer,
                "deliver:%s:done:t%d" % (peer, tid.binary()[0]),
                lambda wh=self.workers[peer], t=tid, m=metas: (
                    self.sched._on_worker_message(wh, ("done", t.binary(), True, m))
                ),
            )
        elif kind == "serve_drain":
            token = msg[1]
            self.queue_delivery(
                peer,
                "deliver:%s:drained:%d" % (peer, token),
                lambda wh=self.workers[peer], tok=token: (
                    self.sched._on_worker_message(
                        wh, ("serve_drained", tok, True, 0)
                    )
                ),
            )
        # cancel_queued / own_meta / stacks / shutdown / resp: no reply.

    # -- event plumbing
    def queue_delivery(self, peer: str, key: str, thunk: Callable[[], None],
                       front: bool = False) -> None:
        q = self.channels.setdefault(peer, deque())
        if front:
            q.appendleft((key, thunk))
        else:
            q.append((key, thunk))

    def arm(self, key: str, thunk: Callable[[], None]) -> None:
        self.globals_[key] = thunk

    def arm_crash(self, name: str) -> None:
        self.arm("crash:%s" % name, lambda n=name: self._crash(n))

    def _crash(self, name: str) -> None:
        wh = self.workers[name]
        self.crashed.add(name)
        self.channels[name].clear()
        self.conns[name].closed = True
        wh.process.mark_dead()
        self.sched._on_worker_death(wh)

    def hb_check(self, vnow: float) -> None:
        """Run the staleness detector at virtual time `vnow` (seconds after
        setup). The throttle is reset so each armed verdict actually runs."""
        self.vclock = max(self.vclock, vnow)
        self.sched._last_hb_check = 0.0
        self.sched._check_heartbeats(self.t0 + vnow)

    t0 = 0.0  # stamped by scenarios that use the virtual clock

    def enabled(self) -> List[str]:
        keys = [
            q[0][0]
            for peer, q in self.channels.items()
            if q and peer not in self.crashed
        ]
        keys.extend(self.globals_.keys())
        return sorted(keys)

    def fire(self, key: str) -> bool:
        thunk = self.globals_.pop(key, None)
        if thunk is None:
            for peer, q in self.channels.items():
                if q and peer not in self.crashed and q[0][0] == key:
                    thunk = q.popleft()[1]
                    break
        if thunk is None:
            return False
        try:
            thunk()
            self.sched._schedule()
            self.sched._flush_outbound()
        except AssertionError as e:
            self.violations.append("%s: %s" % (key, e))
        except Exception as e:  # noqa: BLE001 - a handler crash IS a finding
            self.violations.append(
                "%s: handler raised %s: %s" % (key, type(e).__name__, e)
            )
        return True

    def settle(self) -> None:
        """Initial scheduling pass + flush (the part of the schedule that is
        not permuted: submission order is fixed by the scenario)."""
        try:
            self.sched._schedule()
            self.sched._flush_outbound()
        except AssertionError as e:
            self.violations.append("settle: %s" % e)

    def run_keys(self, keys: List[str]) -> Optional[str]:
        for k in keys:
            if not self.fire(k):
                return "schedule replay mismatch: %r not enabled (have %r)" % (
                    k, self.enabled()
                )
        return None


def base_invariants(h: Harness) -> List[str]:
    """Quiescence invariants shared by every scenario."""
    fails = list(h.violations)
    fails.extend(
        "lifecycle monitor: %s" % v
        for v in lifecycle.violations()
        if not any(v in f for f in fails)
    )
    for key, n in h.seal_counts.items():
        if n > 1:
            fails.append(
                "object %s sealed non-error %d times (double-seal)"
                % (key.hex()[:12], n)
            )
    for rec in h.sched.tasks.values():
        if rec.state in ("PENDING", "RUNNING"):
            fails.append(
                "task t%d stuck %s at quiescence (lost task)"
                % (rec.spec.task_id.binary()[0], rec.state)
            )
    return fails


# --------------------------------------------------------------------- scenarios
class Scenario:
    def __init__(self, name: str, setup: Callable[[Harness], None],
                 check: Optional[Callable[[Harness], List[str]]] = None):
        self.name = name
        self._setup = setup
        self._check = check

    def setup(self, h: Harness) -> None:
        self._setup(h)

    def check(self, h: Harness) -> List[str]:
        fails = base_invariants(h)
        if self._check is not None:
            fails.extend(self._check(h))
        return fails


def _setup_submit_vs_worker_death(h: Harness) -> None:
    # One CPU, two identical tasks -> the second lease-pipelines onto w1's
    # in-flight window. Racing: w1's two done deliveries (FIFO), w1's crash
    # (retries re-dispatch to a fresh worker), and a worker-SUSPECT verdict.
    import time as _time

    h.t0 = _time.time()
    h.add_node({"CPU": 1.0})
    h.submit(1, max_retries=1)
    h.submit(2, max_retries=1)
    h.settle()
    h.arm_crash("w1")
    h.arm("verdict:workers", lambda: h.hb_check(3.0))


def _setup_seal_vs_owner_death(h: Harness) -> None:
    # w1 runs the parent task and, mid-execution, submits a child task it
    # OWNS (cmd submit over its conn, before its own done in the FIFO). The
    # child runs on w2. w1's crash races the child's dispatch and seal:
    # owner death must cancel what it can and tolerate the rest.
    def submit_child(hh: Harness, peer: str, req: ExecRequest) -> None:
        child = hh.make_record(2)
        hh.queue_delivery(
            peer,
            "deliver:%s:submit:t2" % peer,
            lambda wh=hh.workers[peer], rec=child: (
                hh.sched._on_worker_message(wh, ("cmd", "submit", rec))
            ),
        )

    h.exec_hooks[1] = submit_child
    h.add_node({"CPU": 2.0})
    h.submit(1)
    h.settle()
    h.arm_crash("w1")


def _check_seal_vs_owner_death(h: Harness) -> List[str]:
    fails = []
    # A cancelled-by-owner-death child must hold an error seal, never a
    # payload seal racing in afterwards (the late-done guard in
    # _on_task_done): state CANCELLED with a non-error seal is a conflict.
    for rec in h.sched.tasks.values():
        if rec.state == "CANCELLED":
            for oid in rec.return_ids:
                if h.seal_counts.get(oid.binary()):
                    fails.append(
                        "cancelled task t%d has a non-error seal"
                        % rec.spec.task_id.binary()[0]
                    )
    return fails


def _setup_hb_verdict_vs_rejoin(h: Harness) -> None:
    # Daemon-backed node. Verdicts run the real detector at virtual times
    # 2.5s (SUSPECT window: > 2 periods) and 6.0s (> grace of 5s). The
    # daemon's late heartbeat races them; the real handler stamps wall time,
    # so the harness re-stamps to the virtual arrival time (vclock + 1s) —
    # that is the one clock shim, everything else is handler code.
    import time as _time

    h.t0 = _time.time()
    nid, daemon = h.add_daemon_node({"CPU": 1.0})
    h.hb_nid = nid

    def rejoin():
        h.sched._on_daemon_message(daemon, ("heartbeat",))
        node = h.sched.nodes.get(nid)
        if node is not None:
            node.last_heartbeat = h.t0 + h.vclock + 1.0

    h.queue_delivery("d1", "deliver:d1:heartbeat", rejoin)
    h.arm("verdict:suspect", lambda: h.hb_check(2.5))
    h.arm("verdict:dead", lambda: h.hb_check(6.0))


def _check_hb_verdict_vs_rejoin(h: Harness) -> List[str]:
    fails = []
    node = h.sched.nodes.get(h.hb_nid)
    if node is not None and node.health == "DEAD":
        fails.append("node declared DEAD but still in the node table")
    if node is not None and not node.alive:
        fails.append("node marked not-alive but still in the node table")
    return fails


def _setup_drain_vs_kill(h: Harness) -> None:
    # Graceful serve drain of an actor's worker racing that worker's death
    # and the drain-deadline sweep. The reply future must resolve exactly
    # once on every interleaving (reply, death-completes-drain, or timeout).
    import concurrent.futures

    nid = h.add_node({"CPU": 1.0})
    node = h.sched.nodes[nid]
    wh = h.sched._spawn_worker(node, actor_id=None)
    node.idle.remove(wh.worker_id)
    aid = ActorID(bytes([9]) * ActorID.SIZE)
    wh.actor_id = aid
    wh.state = "busy"
    creation = ExecRequest(
        spec=TaskSpec(
            task_id=TaskID(bytes([9]) * TaskID.SIZE),
            func=FunctionDescriptor("fid", "A"),
            actor_id=aid,
            is_actor_creation=True,
        ),
        arg_metas=[],
        kwarg_metas={},
        return_ids=[],
    )
    h.sched.actors[aid] = ActorRecord(
        actor_id=aid, creation_req=creation, resources={},
        worker=wh.worker_id, node=nid, state="ALIVE",
    )
    h.drain_fut = concurrent.futures.Future()
    h.sched._start_serve_drain(aid.binary(), 5.0, ("future", h.drain_fut))
    h.settle()
    h.arm_crash("w1")
    import time as _time

    h.arm(
        "sweep:deadline",
        lambda: h.sched._sweep_serve_drains(_time.time() + 60.0),
    )


def _check_drain_vs_kill(h: Harness) -> List[str]:
    fails = []
    if not h.drain_fut.done():
        fails.append("drain future unresolved at quiescence")
    if h.sched._serve_drains:
        fails.append("drain table non-empty at quiescence")
    return fails


SCENARIOS: Dict[str, Scenario] = {
    "submit_vs_worker_death": Scenario(
        "submit_vs_worker_death", _setup_submit_vs_worker_death
    ),
    "seal_vs_owner_death": Scenario(
        "seal_vs_owner_death", _setup_seal_vs_owner_death,
        _check_seal_vs_owner_death,
    ),
    "hb_verdict_vs_rejoin": Scenario(
        "hb_verdict_vs_rejoin", _setup_hb_verdict_vs_rejoin,
        _check_hb_verdict_vs_rejoin,
    ),
    "drain_vs_kill": Scenario(
        "drain_vs_kill", _setup_drain_vs_kill, _check_drain_vs_kill
    ),
}


# --------------------------------------------------------------------- exploration
class ExploreResult:
    def __init__(self, scenario: str):
        self.scenario = scenario
        self.schedules_run = 0  # harness executions (the budget unit)
        self.complete: List[List[str]] = []  # schedules that reached quiescence
        self.failures: List[Tuple[List[str], List[str]]] = []
        self.truncated = False

    @property
    def ok(self) -> bool:
        return not self.failures


def _peer_of(key: str) -> Optional[str]:
    if key.startswith("deliver:"):
        return key.split(":", 2)[1]
    return None  # crash / verdict / sweep: dependent with everything


def _independent(a: str, b: str) -> bool:
    pa, pb = _peer_of(a), _peer_of(b)
    return pa is not None and pb is not None and pa != pb


def _execute_prefix(scenario: Scenario, prefix: List[str], sched_cls,
                    result: ExploreResult) -> Tuple[Harness, Optional[str]]:
    h = Harness(sched_cls=sched_cls)
    err = None
    try:
        scenario.setup(h)
        err = h.run_keys(prefix)
    except AssertionError as e:
        h.violations.append("setup: %s" % e)
    result.schedules_run += 1
    return h, err


def explore(scenario, budget: int = DEFAULT_BUDGET, seed: int = DEFAULT_SEED,
            sched_cls=VirtualScheduler, max_steps: int = MAX_STEPS,
            ) -> ExploreResult:
    """Bounded DFS over delivery orders and crash points. Deterministic for
    a given (scenario, seed, budget, sched_cls): the seed only permutes
    sibling visit order, so two runs produce identical schedule sets."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    result = ExploreResult(scenario.name)

    def dfs(prefix: List[str], sleep: frozenset) -> None:
        if result.truncated or result.schedules_run >= budget:
            result.truncated = True
            return
        h, err = _execute_prefix(scenario, prefix, sched_cls, result)
        try:
            if err is not None:
                result.failures.append((list(prefix), [err]))
                return
            enabled = h.enabled()
            if not enabled:
                result.complete.append(list(prefix))
                msgs = scenario.check(h)
                if msgs:
                    result.failures.append((list(prefix), msgs))
                return
            if len(prefix) >= max_steps:
                result.failures.append(
                    (list(prefix),
                     ["no quiescence within %d events" % max_steps])
                )
                return
            candidates = [e for e in enabled if e not in sleep]
            rng = random.Random("%d|%s" % (seed, "|".join(prefix)))
            rng.shuffle(candidates)
            done: set = set()
            for e in candidates:
                child_sleep = frozenset(
                    s for s in (set(sleep) | done) if _independent(s, e)
                )
                dfs(prefix + [e], child_sleep)
                done.add(e)
                if result.truncated:
                    return
        finally:
            h.close()

    dfs([], frozenset())
    return result


def replay(scenario, schedule: List[str], sched_cls=VirtualScheduler,
           ) -> Tuple[bool, List[str]]:
    """Re-run one recorded schedule. Returns (ok, messages); a key that is
    no longer enabled at its position is a determinism/compat failure."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    h = Harness(sched_cls=sched_cls)
    try:
        scenario.setup(h)
        err = h.run_keys(schedule)
        if err is not None:
            return False, [err]
        if h.enabled():
            # Partial schedule (a recorded failure prefix): legality of the
            # prefix is all that is checked.
            return (not h.violations), list(h.violations)
        msgs = scenario.check(h)
        return (not msgs), msgs
    finally:
        h.close()


# --------------------------------------------------------------------- corpus + sweep
def _corpus_path(name: str) -> str:
    return os.path.join(CORPUS_DIR, name + ".json")


def _load_corpus(name: str) -> Optional[dict]:
    try:
        with open(_corpus_path(name), "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _save_corpus(name: str, seed: int, result: ExploreResult) -> None:
    entry = {
        "scenario": name,
        "seed": seed,
        "schedules_explored": len(result.complete),
        # A spread of complete schedules: first/last plus evenly spaced
        # middles — enough to replay the interesting orders cheaply.
        "schedules": _spread(result.complete, 16),
        "failures": [
            {"schedule": sch, "messages": msgs}
            for sch, msgs in result.failures[:8]
        ],
    }
    try:
        os.makedirs(CORPUS_DIR, exist_ok=True)
        with open(_corpus_path(name), "w", encoding="utf-8") as f:
            json.dump(entry, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass  # read-only checkout: exploration still ran


def _spread(items: List[List[str]], k: int) -> List[List[str]]:
    if len(items) <= k:
        return items
    step = (len(items) - 1) / (k - 1)
    return [items[round(i * step)] for i in range(k)]


def run_sweep(names: List[str], budget: int = DEFAULT_BUDGET,
              seed: int = DEFAULT_SEED, quiet: bool = False) -> bool:
    """Corpus replay + fresh exploration for each scenario. The CLI entry
    (`python -m ray_tpu.devtools.verify <pkg> --explore ...`)."""
    ok = True
    for name in names:
        scenario = SCENARIOS[name]
        corpus = _load_corpus(name)
        replay_fail = 0
        if corpus:
            for sch in corpus.get("schedules", []):
                good, msgs = replay(scenario, sch)
                if not good:
                    replay_fail += 1
                    ok = False
                    if not quiet:
                        for m in msgs:
                            print("rt-verify explore %s REPLAY: %s" % (name, m))
        result = explore(scenario, budget=budget, seed=seed)
        if result.failures:
            ok = False
            if not quiet:
                for sch, msgs in result.failures[:4]:
                    print(
                        "rt-verify explore %s FAIL schedule=%s" % (name, sch)
                    )
                    for m in msgs:
                        print("    %s" % m)
        if not quiet:
            print(
                "rt-verify explore %s: %d executions, %d complete schedules"
                "%s%s%s"
                % (
                    name,
                    result.schedules_run,
                    len(result.complete),
                    " (budget-truncated)" if result.truncated else "",
                    ", %d corpus replay failure(s)" % replay_fail
                    if replay_fail
                    else "",
                    ", %d failing schedule(s)" % len(result.failures)
                    if result.failures
                    else ", all invariants held",
                )
            )
        _save_corpus(name, seed, result)
    return ok
