"""rt-verify: system-level verification for the ray_tpu control plane — the
step up from rt-lint's per-site checks to whole-protocol / whole-binary ones.

Where rt-lint asks "is this call site well-formed?", rt-verify asks "can the
SYSTEM misbehave?": the wire protocol has stateful rules (request/reply token
pairing, transfer_begin -> transfer_chunk* -> transfer_end streams, per-role
tag ownership) that no arity check sees, and the native extensions decode
untrusted network bytes in hand-rolled C where a missed bounds check is a
crash or a multi-GB allocation, not a traceback.

Static passes (pure stdlib, never import the runtime — same contract as
rt-lint; shared parsed-AST cache in devtools.astutil):

  session    -- every sender site's module role and the session spec's own
                coherence checked against protocol.SESSION_SPEC +
                MESSAGE_GRAMMAR (pairs reply in the reverse direction,
                stream tags exist, no module speaks a role it doesn't own)
  lockorder  -- lock-acquisition graph over `with self._lock:` /
                `@lock_guarded` sites across the tree; any cycle (potential
                deadlock between PullManager/PushManager/OwnershipTable/
                BatchedSender/scheduler locks) is a violation
  native     -- C-source checks over _native/wire_native.c + shm_arena.cpp:
                unchecked PyMem_Malloc/Realloc, owned references leaked on
                error-return paths, length fields used in memcpy/allocation
                without a preceding bounds check
  stale      -- the checked-in .so binaries must embed the sha256 of the
                source they were built from (drift fails the run)

Dynamic verification (same CLI):

  fuzz       -- structure-aware mutation fuzzer over BOTH wire codecs (the C
                extension and its pure-Python twin): seeded + replayable,
                corpus persisted under tools/fuzz_corpus/, asserting typed
                rejection (WireDecodeError), reject-parity between the
                twins, and bounded time/allocation per case; crashing
                inputs are written to tools/fuzz_corpus/crashers/

Runtime conformance (not in this package, but generated from the same spec):
`ray_tpu._private.session_monitor` compiles SESSION_SPEC into per-connection
monitors armed by RAY_TPU_DEBUG_INVARIANTS=1 — out-of-state frames raise in
live mini-clusters, so the invariants-armed test suites exercise the session
machine end to end.

Entry point::

    python -m ray_tpu.devtools.verify [package_dir] [--passes ...]
        [--fuzz N] [--allowlist FILE]

Violations use the rt-lint allowlist model (verify_allowlist.txt next to
this package: stable keys, mandatory ` -- justification`, stale entries
fail).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_ALLOWLIST = os.path.join(_HERE, "verify_allowlist.txt")
# The shipped package's native dir — the fallback when run_all is given no
# package_dir-derived location (single definition; pass_native and stale
# import it from here).
DEFAULT_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)),
                                  "_native")


def run_all(package_dir: str, passes: Optional[List[str]] = None,
            allowlist_path: Optional[str] = None,
            native_dir: Optional[str] = None) -> Tuple[list, List[str]]:
    """Run the static verify passes; returns (violations, errors) with the
    allowlist applied — the same contract as lint.run_all, over the same
    shared parsed-AST cache."""
    from ray_tpu.devtools import report
    from ray_tpu.devtools.astutil import load_package
    from ray_tpu.devtools.verify import (
        pass_lockorder, pass_native, pass_session, stale,
    )

    if native_dir is None:
        # Verify the TARGET tree's native sources/binaries, not whichever
        # installation this module was imported from.
        cand = os.path.join(package_dir, "_native")
        native_dir = cand if os.path.isdir(cand) else DEFAULT_NATIVE_DIR

    table: Dict[str, object] = {
        "session": pass_session.run,
        "lockorder": pass_lockorder.run,
        "native": lambda pkg: pass_native.run(pkg, native_dir=native_dir),
        "stale": lambda pkg: stale.run(pkg, native_dir=native_dir),
    }
    pkg = load_package(package_dir, package_name="ray_tpu")
    violations: list = []
    for name in (passes if passes is not None else table):
        violations.extend(table[name](pkg))
    errors: List[str] = []
    if allowlist_path:
        violations, errors = report.apply_allowlist_file(violations, allowlist_path)
    violations.sort(key=lambda v: (v.pass_id, v.path, v.line))
    return violations, errors


PASS_NAMES = ("session", "lockorder", "native", "stale")
