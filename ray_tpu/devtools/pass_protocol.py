"""Protocol-conformance pass: cross-check every control-plane sender site and
reader dispatch loop against protocol.MESSAGE_GRAMMAR.

Senders: calls whose callee name is one of SENDER_METHODS and whose message
argument is a tuple literal with a string tag head — including dynamically
extended tuples like ``("done",) + payload`` (tag registers, arity unchecked)
— plus handshake frames written as ``serialization.dumps((<tuple>))``.

Readers: the dispatch loops named in protocol.DISPATCHERS. Within each, the
pass collects tags from comparisons against a subscript-0 binding (``kind =
msg[0]; kind == "exec"`` or ``msg[0] == "batch"``), including `in`-tuple
membership tests.

Checks:
  P1 unknown-tag         sender uses a tag absent from the grammar
  P2 arity-mismatch      literal tuple length outside the grammar's range
  P3 unhandled-tag       a grammar tag a required dispatcher does not handle
  P4 phantom-tag         a dispatcher handles a tag the grammar doesn't know
  P5 never-sent          a grammar tag with no sender site anywhere
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.astutil import (
    Package, Violation, ancestors, call_name, const_str, dotted, make_key,
)

# Methods through which control messages leave a process. `_send_to` and
# `_send` take the message as the LAST positional arg; the rest take it
# first. `buffer`/`send_async` are the BatchedSender enqueues.
SENDER_METHODS = {
    "send": 0, "send_async": 0, "buffer": 0, "_send": -1, "_send_to": -1,
}

# Modules scanned for sender sites (control-plane only: elsewhere `.send()`
# means sockets/generators, not wire messages).
DEFAULT_SENDER_MODULES = (
    "ray_tpu._private.scheduler",
    "ray_tpu._private.worker",
    "ray_tpu._private.worker_main",
    "ray_tpu._private.node_daemon",
    "ray_tpu._private.batching",
    "ray_tpu._private.head",
    "ray_tpu._private.worker_entry",
    "ray_tpu._private.object_transfer",
)


def _grammar_from_source(pkg: Package) -> Tuple[Optional[dict], Optional[dict]]:
    """ast.literal_eval MESSAGE_GRAMMAR / DISPATCHERS out of protocol.py's
    AST — no runtime import."""
    tree = pkg.module_of("ray_tpu._private.protocol") or pkg.module_of("protocol.py")
    if tree is None:
        return None, None
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id in ("MESSAGE_GRAMMAR", "DISPATCHERS"):
                    try:
                        out[tgt.id] = ast.literal_eval(node.value)
                    except ValueError:
                        pass
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
            if isinstance(tgt, ast.Name) and tgt.id in ("MESSAGE_GRAMMAR", "DISPATCHERS"):
                try:
                    out[tgt.id] = ast.literal_eval(node.value)
                except ValueError:
                    pass
    return out.get("MESSAGE_GRAMMAR"), out.get("DISPATCHERS")


def _message_arg(call: ast.Call, recv: Optional[str], meth: str) -> Optional[ast.AST]:
    idx = SENDER_METHODS[meth]
    if not call.args:
        return None
    if meth in ("send", "send_async", "buffer"):
        # Exclude non-wire senders: socket.send(bytes), generator.send —
        # those never pass a tuple literal, which the caller filters on.
        return call.args[0]
    return call.args[idx]


def _tuple_tag_arity(node: ast.AST) -> Optional[Tuple[str, Optional[int]]]:
    """(tag, arity_or_None) for a message expression: a tuple literal with a
    string head, or ``(<tuple>) + rest`` (arity unknown)."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        inner = _tuple_tag_arity(node.left)
        if inner is not None:
            return inner[0], None
        return None
    if not isinstance(node, ast.Tuple) or not node.elts:
        return None
    tag = const_str(node.elts[0])
    if tag is None:
        return None
    if any(isinstance(e, ast.Starred) for e in node.elts):
        return tag, None
    return tag, len(node.elts)


def _collect_senders(pkg: Package, sender_modules) -> List[Tuple[str, Optional[int], str, int, str]]:
    """[(tag, arity, path, line, enclosing_qualname)] over all sender sites."""
    out = []
    for module in sender_modules:
        tree = pkg.module_of(module)
        if tree is None:
            continue
        path = pkg.paths.get(module, module)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            recv, meth = call_name(node)
            msg_node = None
            if meth in SENDER_METHODS:
                msg_node = _message_arg(node, recv, meth)
            elif meth == "dumps" and recv is not None and \
                    recv.split(".")[-1] in ("serialization", "_ser"):
                msg_node = node.args[0] if node.args else None
            if msg_node is None:
                continue
            got = _tuple_tag_arity(msg_node)
            if got is None:
                continue
            qual = _enclosing_qualname(node)
            out.append((got[0], got[1], path, node.lineno, qual))
    return out


def _enclosing_qualname(node: ast.AST) -> str:
    fn = None
    cls = None
    for anc in ancestors(node):
        if fn is None and isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = anc.name
        if cls is None and isinstance(anc, ast.ClassDef):
            cls = anc.name
    if cls and fn:
        return f"{cls}.{fn}"
    return fn or "<module>"


def _handled_tags(fn_node: ast.AST) -> Set[str]:
    """Tags a dispatch function routes on: string comparisons against names
    bound from a ``<x>[0]`` subscript (or direct ``msg[0] == ...``)."""
    sub0_names: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and _is_sub0(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    sub0_names.add(tgt.id)
    tags: Set[str] = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Compare):
            continue
        left_is_kind = (
            _is_sub0(node.left)
            or (isinstance(node.left, ast.Name) and node.left.id in sub0_names)
        )
        if not left_is_kind:
            continue
        for comp in node.comparators:
            s = const_str(comp)
            if s is not None:
                tags.add(s)
            elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for e in comp.elts:
                    es = const_str(e)
                    if es is not None:
                        tags.add(es)
    return tags


def _is_sub0(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Subscript)
        and isinstance(node.slice, ast.Constant)
        and node.slice.value == 0
    )


def run(pkg: Package, grammar: Optional[dict] = None,
        dispatchers: Optional[Dict[str, str]] = None,
        sender_modules=DEFAULT_SENDER_MODULES) -> List[Violation]:
    violations: List[Violation] = []
    if grammar is None or dispatchers is None:
        g, d = _grammar_from_source(pkg)
        grammar = grammar if grammar is not None else g
        dispatchers = dispatchers if dispatchers is not None else d
    if not grammar:
        return [Violation("protocol", "<grammar>", 0,
                          make_key("protocol", "protocol.py", "missing-grammar"),
                          "MESSAGE_GRAMMAR not found / not a literal in protocol.py")]
    dispatchers = dispatchers or {}

    senders = _collect_senders(pkg, sender_modules)
    sent_tags: Set[str] = set()
    for tag, arity, path, line, qual in senders:
        spec = grammar.get(tag)
        if spec is None:
            violations.append(Violation(
                "protocol", path, line,
                make_key("protocol", path, qual, f"tag={tag}", "unknown"),
                f"{qual} sends tag {tag!r} which is not in MESSAGE_GRAMMAR",
            ))
            continue
        sent_tags.add(tag)
        lo, hi = spec["arity"]
        if arity is not None and not (lo <= arity <= hi):
            violations.append(Violation(
                "protocol", path, line,
                make_key("protocol", path, qual, f"tag={tag}", "arity"),
                f"{qual} sends {tag!r} with arity {arity}, grammar says "
                f"[{lo}, {hi}]",
            ))

    # Reader coverage.
    handled_by: Dict[str, Set[str]] = {}
    for disp_key, ref in dispatchers.items():
        module, _, qual = ref.partition(":")
        info = pkg.lookup(f"{module}:{qual}")
        if info is None:
            # Fixture packages use bare module names; fall back to matching
            # on the qualname alone.
            cands = [f for f in pkg.functions.values() if f.qualname == qual]
            info = cands[0] if len(cands) == 1 else None
        if info is None:
            violations.append(Violation(
                "protocol", module, 0,
                make_key("protocol", module, disp_key, "missing-dispatcher"),
                f"dispatcher {disp_key} -> {ref} not found in the tree",
            ))
            continue
        handled_by[disp_key] = _handled_tags(info.node)
        # P4: tags handled that the grammar doesn't know.
        for tag in sorted(handled_by[disp_key] - set(grammar)):
            violations.append(Violation(
                "protocol", info.path, info.node.lineno,
                make_key("protocol", info.path, info.qualname, f"tag={tag}", "phantom"),
                f"{info.qualname} handles tag {tag!r} which is not in "
                f"MESSAGE_GRAMMAR (dead branch or missing registry entry)",
            ))

    for tag, spec in sorted(grammar.items()):
        for disp_key in spec.get("readers", ()):
            if disp_key not in handled_by:
                continue  # dispatcher itself already reported missing
            if tag not in handled_by[disp_key]:
                ref = dispatchers.get(disp_key, disp_key)
                violations.append(Violation(
                    "protocol", ref.partition(":")[0], 0,
                    make_key("protocol", ref.partition(":")[0], disp_key, f"tag={tag}", "unhandled"),
                    f"grammar tag {tag!r} is not handled by required "
                    f"dispatcher {disp_key} ({ref})",
                ))
        # P5: never sent anywhere.
        if tag not in sent_tags:
            violations.append(Violation(
                "protocol", "protocol.py", 0,
                make_key("protocol", "protocol.py", f"tag={tag}", "never-sent"),
                f"grammar tag {tag!r} has no sender site in the tree "
                f"(docstring drift or dead protocol surface)",
            ))
    return violations
