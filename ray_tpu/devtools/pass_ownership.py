"""Ownership discipline: owner-path modules must not reach into the head's
task/object tables directly.

  O1 head-table-access   an owner-path module (`_private/ownership.py`,
                         `_private/worker.py`, `remote_function.py`,
                         `actor.py`) reads or writes a scheduler-owned table
                         (`tasks`, `object_table`, `holders`, `pins`,
                         `lineage_consumers`, `object_waiters`, `pending`)
                         through a scheduler reference

Why: the decentralization contract is that the OWNER process resolves its
objects from its OwnershipTable and everything else goes through the command
queue / request protocol. A direct `scheduler.tasks[...]` from the API layer
would (a) race the loop thread (those tables are loop-thread-only state) and
(b) quietly re-centralize bookkeeping the ownership redesign moved out of
the head. The scheduler's own module — and the devtools themselves — are
exempt by construction.

Detection is name-based on purpose (pure stdlib AST, no imports): an
attribute access `X.<table>` where the receiver expression mentions a
scheduler binding (`scheduler`, `sched`, or the `Scheduler` class) in one of
the owner-path modules.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.devtools.astutil import Package, Violation, make_key

OWNER_PATH_MODULES = (
    "._private.ownership",
    "._private.worker",
    ".remote_function",
    ".actor",
)

HEAD_TABLES = {
    "tasks", "object_table", "holders", "pins", "lineage_consumers",
    "object_waiters", "pending",
}

_SCHED_TOKENS = ("scheduler", "sched", "Scheduler")


def _mentions_scheduler(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and any(t in sub.id for t in _SCHED_TOKENS):
            return True
        if isinstance(sub, ast.Attribute) and any(
            t in sub.attr for t in _SCHED_TOKENS
        ):
            return True
    return False


def run(pkg: Package) -> List[Violation]:
    violations: List[Violation] = []
    for module, tree in pkg.modules.items():
        if not module.endswith(OWNER_PATH_MODULES):
            continue
        path = pkg.paths[module]
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in HEAD_TABLES:
                continue
            if not _mentions_scheduler(node.value):
                continue
            violations.append(
                Violation(
                    pass_id="ownership",
                    path=path,
                    line=node.lineno,
                    key=make_key("ownership", path, f"head_table.{node.attr}"),
                    message=(
                        f"owner-path module accesses the head's `{node.attr}` "
                        "table directly; go through the command queue / "
                        "request protocol (or the OwnershipTable) instead — "
                        "those tables are scheduler-loop-thread state"
                    ),
                )
            )
    return violations
