"""Failpoint discipline.

  F1 bad-name       a failpoint name not matching ``[a-z0-9_.]+``
  F2 undocumented   a failpoint name (or dynamic-name prefix) used in code
                    that is missing from the COMPONENTS.md "Robustness"
                    failpoint table — the doc is the chaos-schedule contract:
                    a name you cannot look up is a name you cannot arm

Checked call sites: ``failpoints.fire / maybe_crash / inject_send /
inject_recv / inject_handle_send`` (and their bare-imported forms) with a
first argument that is
either a string literal or a ``"prefix." + expr`` concatenation. For the
concatenated form the documented table must contain the literal prefix (the
doc spells the family as e.g. ``sched.cmd.<method>``). Non-constant names
(internal forwarding inside failpoints.py itself) are skipped — the public
hook sites all use literals by convention.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from ray_tpu.devtools.astutil import (
    Package, Violation, call_name, const_str, make_key,
)

FIRE_FUNCS = {"fire", "maybe_crash", "inject_send", "inject_recv",
              "inject_handle_send"}
NAME_RE = re.compile(r"^[a-z0-9_.]+$")


def _doc_text(doc_path: Optional[str]) -> Optional[str]:
    if doc_path and os.path.exists(doc_path):
        with open(doc_path, "r", encoding="utf-8") as fh:
            return fh.read()
    return None


def _name_of(arg: ast.AST):
    """(name, is_prefix) for a literal or a ``"lit." + expr`` concat; (None,
    False) when the name cannot be resolved statically."""
    s = const_str(arg)
    if s is not None:
        return s, False
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        left = const_str(arg.left)
        if left is not None:
            return left, True
    return None, False


def run(pkg: Package, doc_text: Optional[str] = None,
        doc_path: Optional[str] = None) -> List[Violation]:
    violations: List[Violation] = []
    if doc_text is None:
        doc_text = _doc_text(doc_path)
    reported: Set[str] = set()
    for module, tree in pkg.modules.items():
        path = pkg.paths[module]
        if module.endswith("failpoints"):
            continue  # the registry's internal forwarding, not a hook site
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            recv, meth = call_name(node)
            if meth not in FIRE_FUNCS:
                continue
            if recv is not None and not recv.endswith("failpoints"):
                continue
            name, is_prefix = _name_of(node.args[0])
            if name is None:
                continue
            bare = name.rstrip(".")
            if not NAME_RE.match(bare):
                key = make_key("failpoints", path, f"name.{name}")
                if key not in reported:
                    reported.add(key)
                    violations.append(Violation(
                        "failpoints", path, node.lineno, key,
                        f"failpoint name {name!r} does not match "
                        f"[a-z0-9_.]+",
                    ))
                continue
            if doc_text is not None and name not in doc_text:
                key = make_key("failpoints", path, f"undocumented.{name}")
                if key not in reported:
                    reported.add(key)
                    what = "prefix" if is_prefix else "name"
                    violations.append(Violation(
                        "failpoints", path, node.lineno, key,
                        f"failpoint {what} {name!r} is not listed in the "
                        f"COMPONENTS.md Robustness failpoint table",
                    ))
    return violations
