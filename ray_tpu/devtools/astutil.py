"""Shared AST plumbing for the rt-lint passes: package loading, a function
symbol table with decorator info, parent links for ancestor queries, and the
Violation/allowlist model.

Everything here is pure stdlib on purpose — see the package docstring.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


# --------------------------------------------------------------------- model
@dataclass
class Violation:
    """One finding. `key` is the stable identity used by the allowlist:
    pass id + file basename + symbol(ish) detail, never a line number, so
    entries survive unrelated edits."""

    pass_id: str
    path: str
    line: int
    key: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}\n    key: {self.key}"


def make_key(pass_id: str, path: str, *parts: str) -> str:
    return ":".join([pass_id, os.path.basename(path), *parts])


@dataclass
class FuncInfo:
    module: str          # dotted module name, e.g. "ray_tpu._private.scheduler"
    path: str            # file path (as given to the loader)
    cls: Optional[str]   # enclosing class name, if a method
    name: str            # bare function name
    node: ast.AST        # FunctionDef / AsyncFunctionDef
    decorators: Set[str] = field(default_factory=set)

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qualname}"


class Package:
    """Parsed view of a set of Python files: module ASTs (with parent links)
    plus a function symbol table."""

    def __init__(self) -> None:
        self.modules: Dict[str, ast.Module] = {}
        self.paths: Dict[str, str] = {}
        self.functions: Dict[str, FuncInfo] = {}        # key -> info
        self.by_name: Dict[str, List[FuncInfo]] = {}    # bare name -> infos

    # ---------------------------------------------------------------- loading
    def add_module(self, module: str, path: str, source: str) -> None:
        tree = ast.parse(source, filename=path)
        link_parents(tree)
        self.modules[module] = tree
        self.paths[module] = path
        for cls, fn in iter_functions(tree):
            info = FuncInfo(
                module=module, path=path, cls=cls, name=fn.name, node=fn,
                decorators={decorator_name(d) for d in fn.decorator_list} - {""},
            )
            self.functions[info.key] = info
            self.by_name.setdefault(fn.name, []).append(info)

    def module_of(self, path_or_module: str) -> Optional[ast.Module]:
        if path_or_module in self.modules:
            return self.modules[path_or_module]
        for mod, p in self.paths.items():
            if p == path_or_module or os.path.basename(p) == path_or_module:
                return self.modules[mod]
        return None

    def lookup(self, ref: str) -> Optional[FuncInfo]:
        """Resolve "module:Class.method" / "module:function"."""
        return self.functions.get(ref)


# One parsed Package per (root, name, excludes) per process, revalidated by
# a cheap per-file (mtime_ns, size) signature walk. Every rt-lint pass, every
# rt-verify pass, and every test that loads the live tree shares ONE parse
# (parsing ~250 files costs ~1s; the suite used to pay it per run_all call
# inside tier-1). Passes treat Package as read-only by contract.
_pkg_cache: dict = {}


def _tree_signature(root: str, exclude: Sequence[str]) -> tuple:
    sig = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d != "__pycache__"
            and not (os.path.relpath(dirpath, root) == "." and d in exclude)
        )
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                fpath = os.path.join(dirpath, fname)
                try:
                    st = os.stat(fpath)
                except OSError:
                    continue
                sig.append((fpath, st.st_mtime_ns, st.st_size))
    return tuple(sig)


def load_package(root: str, package_name: Optional[str] = None,
                 exclude: Sequence[str] = ("devtools",)) -> Package:
    """Parse every .py under `root` (a package directory or a single file).
    Module names are dotted paths rooted at `package_name` (defaults to the
    directory's basename). `exclude` prunes top-level subpackage names.
    Results are cached per process and revalidated by file stat signature;
    callers must treat the returned Package as read-only."""
    pkg = Package()
    if os.path.isfile(root):
        name = os.path.splitext(os.path.basename(root))[0]
        with open(root, "r", encoding="utf-8") as fh:
            pkg.add_module(name, root, fh.read())
        return pkg
    cache_key = (os.path.abspath(root), package_name, tuple(exclude))
    sig = _tree_signature(root, exclude)
    cached = _pkg_cache.get(cache_key)
    if cached is not None and cached[0] == sig:
        return cached[1]
    base = package_name or os.path.basename(os.path.normpath(root))
    for fpath, _mtime, _size in sig:
        rel = os.path.relpath(fpath, root)
        parts = rel[:-3].split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        module = ".".join([base, *parts]) if parts else base
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                pkg.add_module(module, fpath, fh.read())
        except SyntaxError:
            # A file the runtime can't import either; not lint's problem.
            continue
        except OSError:
            continue  # vanished between the signature walk and the read
    _pkg_cache[cache_key] = (sig, pkg)
    return pkg


# ----------------------------------------------------------------- AST utils
def link_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._rt_parent = node  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_rt_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_rt_parent", None)


def iter_functions(tree: ast.Module):
    """Yield (class_name_or_None, FunctionDef) for every def in the module,
    attributing nested defs to their enclosing class (one level: methods of
    nested classes keep the innermost class name)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = None
            for anc in ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    cls = anc.name
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # Nested function: attribute to the outer def's class so
                    # closure helpers stay reachable in the call graph.
                    continue
            yield cls, node


def walk_body(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function WITHOUT descending into nested defs or lambdas: code
    in a nested function runs when (and where — often another thread, or a
    deferred callback) it is CALLED, not where it is defined, so its calls
    must not be attributed to the enclosing function."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def decorator_name(node: ast.AST) -> str:
    """Bare name of a decorator: @x, @mod.x, @x(...), @mod.x(...) -> "x"."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def call_name(node: ast.Call) -> Tuple[Optional[str], str]:
    """(receiver_dotted_or_None, method_name) for a Call: f() -> (None, "f"),
    a.b.c() -> ("a.b", "c")."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return None, fn.id
    if isinstance(fn, ast.Attribute):
        return dotted(fn.value), fn.attr
    return None, ""


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as "a.b.c"; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def has_timeout_arg(call: ast.Call) -> bool:
    """True if the call plausibly passes a bound — a positional arg that is
    not literally None/True (``.wait(None)`` and ``.acquire(True)`` are
    unbounded waits spelled with an argument), or a ``timeout=`` keyword
    whose value is not literally None."""
    for a in call.args:
        if isinstance(a, ast.Constant) and (a.value is None or a.value is True):
            continue
        return True
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant) and kw.value.value is None)
    return False


def imported_names(tree: ast.Module) -> Dict[str, str]:
    """Map local name -> source ("module" or "module.attr") for top-level
    imports, so passes can resolve `from x import y` call sites."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return out


# ------------------------------------------------------------------ allowlist
@dataclass
class AllowEntry:
    key: str
    justification: str
    line_no: int
    used: bool = False


def load_allowlist(path: str) -> Tuple[List[AllowEntry], List[str]]:
    """Parse the allowlist. Line format::

        <violation key> -- <justification>

    '#' lines and blanks are comments. Returns (entries, format_errors);
    an entry with no justification is a format error — the allowlist is
    line-by-line justified by construction."""
    entries: List[AllowEntry] = []
    errors: List[str] = []
    if not os.path.exists(path):
        return entries, errors
    with open(path, "r", encoding="utf-8") as fh:
        for i, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            key, sep, why = line.partition(" -- ")
            if not sep or not why.strip():
                errors.append(
                    f"{path}:{i}: allowlist entry has no ' -- <justification>': {line!r}"
                )
                continue
            entries.append(AllowEntry(key=key.strip(), justification=why.strip(), line_no=i))
    return entries, errors


def apply_allowlist(violations: List[Violation], entries: List[AllowEntry]
                    ) -> Tuple[List[Violation], List[AllowEntry]]:
    """Filter violations through the allowlist. Returns (remaining, unused
    entries). Matching is exact on the stable key."""
    by_key: Dict[str, AllowEntry] = {e.key: e for e in entries}
    remaining: List[Violation] = []
    for v in violations:
        ent = by_key.get(v.key)
        if ent is not None:
            ent.used = True
        else:
            remaining.append(v)
    unused = [e for e in entries if not e.used]
    return remaining, unused
