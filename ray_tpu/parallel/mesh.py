"""Device-mesh construction and sharding rules: the single vocabulary for
DP/FSDP/TP/PP/CP/EP across the framework.

The reference has no first-class parallelism beyond DP (SURVEY.md §2 inventory:
TP/PP/SP/EP all "NO"); its substrate is NCCL p2p. The TPU build instead makes the
mesh the core abstraction (the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives):

 - `MeshSpec(data=, fsdp=, tensor=, pipeline=, context=, expert=)` names the six
   axes. Device order puts `tensor` innermost so tensor-parallel collectives ride
   the fastest ICI links, then context, expert, fsdp, pipeline, data outermost
   (data-parallel gradient reduction tolerates DCN).
 - `ShardingRules` maps *logical* array axes ("batch", "embed", "heads", ...) to
   mesh axes, so models annotate semantics and the trainer decides placement —
   the ScalingConfig -> mesh seam Train uses (SURVEY.md §7 step 5).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("data", "fsdp", "pipeline", "expert", "context", "tensor")


@dataclass(frozen=True)
class MeshSpec:
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    pipeline: int = 1
    context: int = 1
    expert: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, _FIELD_FOR_AXIS[a]) for a in AXIS_ORDER)

    @property
    def num_devices(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def build(self, devices: Optional[Sequence] = None):
        """Build a jax.sharding.Mesh over `devices` (default: all devices)."""
        import jax
        from jax.sharding import Mesh

        devs = list(devices) if devices is not None else jax.devices()
        if len(devs) != self.num_devices:
            raise ValueError(
                f"MeshSpec wants {self.num_devices} devices "
                f"({dict(zip(AXIS_ORDER, self.shape))}), got {len(devs)}"
            )
        grid = np.array(devs).reshape(self.shape)
        return Mesh(grid, AXIS_ORDER)

    @classmethod
    def for_data_parallel(cls, num_devices: int) -> "MeshSpec":
        return cls(data=num_devices)

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshSpec":
        return cls(**{k: int(v) for k, v in d.items()})

    def replace(self, **kw) -> "MeshSpec":
        import dataclasses

        return dataclasses.replace(self, **kw)


_FIELD_FOR_AXIS = {
    "data": "data",
    "fsdp": "fsdp",
    "pipeline": "pipeline",
    "expert": "expert",
    "context": "context",
    "tensor": "tensor",
}


# --------------------------------------------------------------------------- logical sharding rules
Rule = Tuple[str, Optional[Tuple[str, ...]]]


@dataclass
class ShardingRules:
    """Logical-axis -> mesh-axis mapping, applied to model annotations.

    The default rules implement the standard transformer recipe:
      batch over (data, fsdp); embed over fsdp (ZeRO-3 style parameter shard);
      mlp/heads over tensor (megatron style); sequence over context (ring/
      all-to-all attention); experts over expert.
    """

    rules: Tuple[Rule, ...] = (
        ("batch", ("data", "fsdp")),
        ("sequence", ("context",)),
        ("embed", ("fsdp",)),
        ("mlp", ("tensor",)),
        ("heads", ("tensor",)),
        ("kv_heads", ("tensor",)),
        ("vocab", ("tensor",)),
        ("expert", ("expert",)),
        # Layer stacks shard over the pipeline axis (each stage group stores
        # n_layer/pipeline layers); on pipeline=1 meshes the axis is dropped by
        # the divisibility filter and layers stay replicated.
        ("layers", ("pipeline",)),
        ("stage", ("pipeline",)),
        ("head_dim", None),
        ("norm", None),
    )

    def mesh_axes(
        self,
        logical_axes: Sequence[Optional[str]],
        mesh=None,
        shape: Optional[Sequence[int]] = None,
    ):
        """PartitionSpec for an array annotated with logical axis names.

        With `mesh` + `shape`, mesh axes that don't divide the dimension are
        dropped (e.g. 2 heads on a tensor=4 mesh stay replicated) — models keep
        one annotation set across every mesh size.
        """
        from jax.sharding import PartitionSpec

        lookup = dict(self.rules)
        out: List = []
        used: set = set()
        for i, ax in enumerate(logical_axes):
            if ax is None:
                out.append(None)
                continue
            if ax not in lookup:
                raise ValueError(f"no sharding rule for logical axis '{ax}'")
            mesh_axes = lookup[ax]
            if mesh_axes is None:
                out.append(None)
                continue
            # An axis already consumed by another dimension cannot repeat.
            free = [a for a in mesh_axes if a not in used]
            if mesh is not None and shape is not None:
                # Pick the order-preserving subset of axes with the largest
                # total size that divides the dimension (a greedy prefix would
                # e.g. keep data=2 and then have to drop fsdp=8 on a dim of 8,
                # silently losing 4x parallelism).
                import itertools

                dim = shape[i]
                candidates = [a for a in free if mesh.shape[a] > 1]
                best: List[str] = []
                best_prod = 1
                # Exhaustive over subsets (rules map to <=3 axes, so <=8): a
                # larger subset is not necessarily a larger product.
                for r in range(len(candidates), 0, -1):
                    for combo in itertools.combinations(candidates, r):
                        prod = 1
                        for a in combo:
                            prod *= mesh.shape[a]
                        if dim % prod == 0 and prod > best_prod:
                            best, best_prod = list(combo), prod
                free = best
            used.update(free)
            if not free:
                out.append(None)
            elif len(free) == 1:
                out.append(free[0])
            else:
                out.append(tuple(free))
        return PartitionSpec(*out)

    def sharding(self, mesh, logical_axes: Sequence[Optional[str]], shape=None):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.mesh_axes(logical_axes, mesh=mesh, shape=shape))


def batch_spec():
    from jax.sharding import PartitionSpec

    return PartitionSpec(("data", "fsdp"), "context")


def batch_sharding(mesh):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, batch_spec())


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


# --------------------------------------------------------------------------- host<->global helpers
def host_local_to_global(mesh, spec, array):
    """Per-host shard -> global jax.Array (multi-controller boundary helper)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(NamedSharding(mesh, spec), array)


def global_to_host_local(garr) -> np.ndarray:
    """This host's shards of a global array, concatenated (inverse of above for
    fully-addressable layouts)."""
    shards = sorted(garr.addressable_shards, key=lambda s: s.index)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0) if shards else np.asarray(garr)


def shard_params(params, mesh, rules: ShardingRules, logical_axes):
    """device_put a pytree of host params according to per-leaf logical axes."""
    import jax

    return jax.tree.map(
        lambda p, ax: jax.device_put(p, rules.sharding(mesh, ax, shape=p.shape)),
        params,
        logical_axes,
    )
