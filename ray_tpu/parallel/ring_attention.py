"""Ring attention: context-parallel exact attention over the "context" mesh axis.

Sequence length S is sharded S/cp per device. Each device keeps its Q shard and
rotates K/V shards around the ring with `lax.ppermute` (ICI neighbor links),
folding each incoming block into an online-softmax accumulator — O(S/cp) memory
per device, exact results, overlappable comm/compute. This is the long-context
capability SURVEY.md §5 calls out as absent from the reference ("SP: NO — must
be designed fresh").

`ring_attention` is written to run *inside* shard_map (it uses the axis name);
`ring_attention_sharded` wraps it for a (batch, heads, seq, head_dim) global
array on a mesh with a "context" axis. Alternative head-sharded (Ulysses /
all-to-all) attention is `ulysses_attention` below.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import shard_map as _shard_map

NEG_INF = -1e30


def ring_attention(
    q,
    k,
    v,
    axis_name: str = "context",
    axis_size: Optional[int] = None,
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """Exact attention with K/V rotating around the `axis_name` ring.

    Args (per-device shards): q, k, v of shape (batch, heads, s_local, head_dim).
    Must be called inside shard_map/jit over a mesh containing `axis_name`.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if axis_size is None:
        axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape

    qf = q.astype(jnp.float32)
    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)

    # After `step` rotations each device holds the K/V shard that originated at
    # (my - step) mod n: perm sends shard i -> i+1 each step.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step_fn(carry, step):
        m_prev, l_prev, acc, k_cur, v_cur = carry
        src = jax.lax.rem(my - step + axis_size, axis_size)

        def attend(args):
            m_prev, l_prev, acc, k_cur, v_cur = args
            s = jnp.einsum(
                "bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ) * sm_scale
            if causal:
                row = my * s_local + jax.lax.broadcasted_iota(jnp.int32, (s_local, s_local), 0)
                col = src * s_local + jax.lax.broadcasted_iota(jnp.int32, (s_local, s_local), 1)
                s = jnp.where((row >= col)[None, None], s, NEG_INF)
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        if causal:
            # K/V blocks entirely in this device's future contribute nothing:
            # skip the quadratic compute (the branch condition is identical on
            # every device for a given step, so control flow stays uniform).
            m_new, l_new, acc_new = jax.lax.cond(
                src > my,
                lambda args: (args[0], args[1], args[2]),
                attend,
                (m_prev, l_prev, acc, k_cur, v_cur),
            )
        else:
            m_new, l_new, acc_new = attend((m_prev, l_prev, acc, k_cur, v_cur))
        # Rotate K/V to the next device; XLA overlaps this with the next step's
        # compute when it can (double-buffered ring).
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_next, v_next), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        step_fn, (m0, l0, acc0, k, v), jnp.arange(axis_size)
    )
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention_sharded(mesh, q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """shard_map wrapper: global (batch, heads, seq, head_dim) arrays with seq
    sharded over the mesh's "context" axis, batch over (data, fsdp)."""
    from jax.sharding import PartitionSpec as P

    axis_size = mesh.shape["context"]
    spec = P(("data", "fsdp"), None, "context", None)
    fn = _shard_map(
        functools.partial(
            ring_attention,
            axis_name="context",
            axis_size=axis_size,
            causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str = "context",
    axis_size: Optional[int] = None,
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """Ulysses-style sequence parallelism: all-to-all swaps the sharded axis
    from sequence to heads, each device runs full-sequence attention for its
    head subset, then all-to-all swaps back. Cheaper than ring when
    heads >= axis_size; requires heads % axis_size == 0.

    Call inside shard_map with q/k/v sharded (batch, heads, seq/cp, head_dim).
    """
    from ray_tpu.ops.flash_attention import xla_attention

    if axis_size is None:
        axis_size = jax.lax.psum(1, axis_name)
    b, h, s_local, d = q.shape

    def seq_to_heads(x):
        # (b, h, s/cp, d) -> all-to-all -> (b, h/cp, s, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = xla_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(oh)
