from ray_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshSpec,
    ShardingRules,
    batch_sharding,
    batch_spec,
    global_to_host_local,
    host_local_to_global,
    replicated,
    shard_params,
)

__all__ = [
    "AXIS_ORDER",
    "MeshSpec",
    "ShardingRules",
    "batch_sharding",
    "batch_spec",
    "global_to_host_local",
    "host_local_to_global",
    "replicated",
    "shard_params",
]
