"""Pipeline parallelism: GPipe-style microbatch schedule over the `pipeline`
mesh axis, SPMD-native.

No reference equivalent (SURVEY.md §2: PP "NO in-tree", only external Alpa,
`/root/reference/release/alpa_tests/train_opt_2_7b_minimum.py`); this is the
TPU-first design the blueprint (§7 step 8) calls for:

 - layer stacks are sharded over `pipeline` on their leading (stage) dim, so
   each device group stores only L/P layers — the memory win PP exists for;
 - only the `pipeline` axis is manual (`shard_map(axis_names={"pipeline"})`);
   data/fsdp/tensor/context stay compiler-managed, so TP/DP/CP collectives are
   still inserted by XLA *inside* each stage;
 - activations advance between stages with `lax.ppermute` over ICI; the
   backward pass pipelines automatically because ppermute/scan transpose to the
   reversed schedule;
 - schedule: M microbatches through P stages in M+P-1 ticks (bubble fraction
   (P-1)/(M+P-1); raise `num_microbatches` to amortize it).

All ranks run every tick (SPMD): ticks where a rank has no real microbatch
compute garbage that is masked out of the result — that idle-compute IS the
pipeline bubble, made explicit.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from ray_tpu._private.jax_compat import shard_map as _shard_map


def pipeline_apply(
    mesh,
    stage_params,
    x,
    block_stack_fn: Callable,
    num_microbatches: int,
    context_manual: bool = False,
    seq_streams: tuple = (),
):
    """Run `block_stack_fn(stage_params_local, x_mb, first_layer_idx)` as a
    P-stage pipeline over microbatches of `x`.

    Args:
      mesh: jax Mesh with a `pipeline` axis of size P > 1.
      stage_params: pytree whose leaves have leading dim P (stage), i.e. layer
        stacks reshaped (L, ...) -> (P, L//P, ...), sharded over `pipeline`.
      x: (B, S, D) activations (embedded tokens).
      block_stack_fn: applies one stage's layer stack to one microbatch:
        (local_params with leading dim L//P, (mb, S, D), first_layer_idx,
        microbatch_idx, seq_streams) -> ((mb, S, D), aux_scalar). The
        microbatch index keeps per-microbatch randomness (dropout)
        independent, matching non-pipelined semantics; aux (e.g. MoE
        load-balance loss) accumulates over REAL ticks only (bubble-tick
        garbage is masked out), summed over stages via psum and averaged over
        microbatches.
      seq_streams: per-position arrays with leading dim S (e.g. RoPE cos/sin
        tables) that must shard with the sequence: inside the region each rank
        sees its context shard, keeping GLOBAL positions correct under CP.
      num_microbatches: M; must divide B.
      context_manual: also make the `context` axis manual inside the pipeline
        region (sequence dim sharded S/cp per rank) so ring attention — which
        runs collectives over the context axis name — can execute inside the
        stage. Required when combining PP with CP: a nested full shard_map
        cannot open a second manual region over an axis of the same mesh.

    Returns ((B, S, D) activations after all L layers, aux scalar), both
    replicated over the pipeline axis (final psum-mask), so the LM head /
    loss can be computed with ordinary auto-sharded ops.
    """
    Pp = mesh.shape["pipeline"]
    B, S, D = x.shape
    M = num_microbatches
    if B % M != 0:
        raise ValueError(f"num_microbatches={M} must divide batch {B}")
    x_mb = x.reshape(M, B // M, S, D)

    def per_rank(stage_local, x_all, *streams):
        # stage_local leaves: (1, L//P, ...) — this rank's stage slice.
        stage_local = jax.tree.map(lambda a: a[0], stage_local)
        p = jax.lax.axis_index("pipeline")
        n_local = jax.tree.leaves(stage_local)[0].shape[0]
        first_layer = p * n_local
        T = M + Pp - 1

        def tick(carry, t):
            buf, out, aux_acc = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            inject = jax.lax.dynamic_index_in_dim(x_all, mb_idx, 0, keepdims=False)
            # Stage 0 feeds fresh microbatches; later stages consume what the
            # previous stage ppermuted over last tick.
            x_in = jnp.where(p == 0, inject, buf)
            # The microbatch this rank is processing at tick t.
            mb_proc = jnp.clip(t - p, 0, M - 1)
            y, aux = block_stack_fn(stage_local, x_in, first_layer, mb_proc, streams)
            # Bubble ticks compute garbage: only real (stage, microbatch)
            # pairs contribute aux.
            real = jnp.logical_and(t - p >= 0, t - p < M)
            aux_acc = aux_acc + jnp.where(real, aux, 0.0)
            # Last stage banks finished microbatch t-(P-1), other ticks/ranks
            # write back the value already there (masked no-op).
            out_idx = jnp.clip(t - (Pp - 1), 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(out, out_idx, 0, keepdims=False)
            valid = jnp.logical_and(p == Pp - 1, t >= Pp - 1)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, cur), out_idx, 0
            )
            buf = jax.lax.ppermute(
                y, "pipeline", [(i, (i + 1) % Pp) for i in range(Pp)]
            )
            return (buf, out, aux_acc), None

        buf0 = jnp.zeros_like(x_all[0])
        out0 = jnp.zeros_like(x_all)
        aux0 = jnp.zeros((), jnp.float32)
        (_, out, aux_acc), _ = jax.lax.scan(tick, (buf0, out0, aux0), jnp.arange(T))
        # Replicate the last stage's results across the pipeline axis; sum
        # stage aux contributions (each stage owns distinct layers).
        out = jax.lax.psum(jnp.where(p == Pp - 1, out, jnp.zeros_like(out)), "pipeline")
        aux_total = jax.lax.psum(aux_acc, "pipeline") / M
        return out, aux_total

    manual = {"pipeline"}
    x_spec = P()
    stream_spec = P()
    if context_manual:
        manual.add("context")
        # x_mb is (M, mb, S, D): shard the sequence dim over context; streams
        # shard their leading (position) dim the same way.
        x_spec = P(None, None, "context", None)
        stream_spec = P("context")
    sharded = _shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(P("pipeline"), x_spec) + (stream_spec,) * len(seq_streams),
        out_specs=(x_spec, P()),
        axis_names=frozenset(manual),
        check_vma=False,
    )
    out, aux = sharded(stage_params, x_mb, *seq_streams)
    return out.reshape(B, S, D), aux


def to_stages(blocks, num_stages: int):
    """Reshape stacked layer params (L, ...) -> (num_stages, L//num_stages, ...)."""

    def split(a):
        L = a.shape[0]
        if L % num_stages != 0:
            raise ValueError(f"n_layer={L} not divisible by pipeline={num_stages}")
        return a.reshape(num_stages, L // num_stages, *a.shape[1:])

    return jax.tree.map(split, blocks)
