"""Exception types, mirroring the reference's `python/ray/exceptions.py`."""

from __future__ import annotations

from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


# Alias matching the reference's naming for drop-in familiarity.
RayError = RayTpuError


class RayTaskError(RayTpuError):
    """Raised at `get()` when the remote task raised; wraps the remote traceback
    (reference: `exceptions.py RayTaskError`, which dynamically subclasses the
    cause so `except OriginalError` works — we replicate that in as_instanceof_cause)."""

    def __init__(self, function_name: str, traceback_str: str, cause: Optional[BaseException], pid: int = 0):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.pid = pid
        super().__init__(
            f"Task {function_name} failed (pid={pid}):\n{traceback_str}"
        )

    def __reduce__(self):
        try:
            import pickle

            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = None
        return (RayTaskError, (self.function_name, self.traceback_str, cause, self.pid))

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is both a RayTaskError and an instance of the
        cause's class, so user `except ValueError:` blocks catch it."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if issubclass(RayTaskError, cause_cls):
            return self
        try:
            derived = type(
                "RayTaskError(" + cause_cls.__name__ + ")",
                (RayTaskError, cause_cls),
                {},
            )
            instance = derived.__new__(derived)
            RayTaskError.__init__(
                instance, self.function_name, self.traceback_str, self.cause, self.pid
            )
            return instance
        except TypeError:
            return self


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class OutOfMemoryError(WorkerCrashedError):
    """The worker was killed by the memory monitor's OOM policy (reference:
    `ray.exceptions.OutOfMemoryError` raised by the raylet's worker-killing
    path, `src/ray/raylet/worker_killing_policy.h`). Retriable: the task is
    resubmitted while retries remain."""


class RayActorError(RayTpuError):
    """The actor died before or during this method call."""


ActorDiedError = RayActorError


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get()` timed out."""


class ObjectStoreFullError(RayTpuError):
    """The node's shared-memory store is over its configured capacity."""


class ObjectLostError(RayTpuError):
    """An object's segment is gone and it cannot be reconstructed."""


class OwnerDiedError(ObjectLostError):
    """The process that owned an object (submitted the task / called put)
    died before the result resolved. Ownership semantics (the reference's
    distributed-futures model): the owner holds the object's record of
    truth, so its death makes unresolved results permanently unavailable —
    dependent `get()`s raise this instead of hanging, and lineage
    reconstruction refuses to re-execute a dead owner's tasks."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class CrossLanguageError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass
