"""Shared Serve dataclasses.

Reference: `python/ray/serve/_private/common.py` (DeploymentInfo,
ReplicaState) and `serve/config.py` (AutoscalingConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

CONTROLLER_NAME = "SERVE_CONTROLLER"
PROXY_NAME = "SERVE_PROXY"
DEFAULT_HTTP_PORT = 8000


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 10
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 10.0

    def __post_init__(self):
        if not (0 < self.min_replicas <= self.max_replicas):
            raise ValueError("need 0 < min_replicas <= max_replicas")


@dataclass
class DeploymentInfo:
    name: str
    blob: bytes  # cloudpickled user class/function
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)
    num_replicas: int = 1
    # In-flight calls one replica accepts concurrently (reference
    # `max_concurrent_queries`, default 100 there). Default 1 keeps the
    # strict one-at-a-time replica; raise it to overlap requests — required
    # for `@serve.batch` to ever see a second item.
    max_concurrent_queries: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    route_prefix: Optional[str] = None
    is_ingress: bool = False
    # True when the target class carries an ASGI app (@serve.ingress): the
    # proxy speaks ASGI to its replicas instead of the ProxyRequest protocol.
    is_asgi: bool = False
    version: int = 0


@dataclass
class ReplicaInfo:
    replica_id: str
    actor_id: Any  # ActorID — picklable
    deployment: str
    # Copied from the deployment so the ROUTER can cap per-replica load
    # decisions (affinity escape) without a controller round trip.
    max_concurrent_queries: int = 1
