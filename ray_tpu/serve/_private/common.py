"""Shared Serve dataclasses.

Reference: `python/ray/serve/_private/common.py` (DeploymentInfo,
ReplicaState) and `serve/config.py` (AutoscalingConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

CONTROLLER_NAME = "SERVE_CONTROLLER"
PROXY_NAME = "SERVE_PROXY"
DEFAULT_HTTP_PORT = 8000


class RequestShedded(Exception):
    """Admission control rejected this request (per-app queue cap at a
    proxy, per-replica inflight cap at the router, a shed-aware
    `@serve.batch` queue, or a draining proxy). The HTTP front door maps it
    to a fast `503 + Retry-After`; handle callers see it raised from
    `.result()`. `reason` feeds `ray_tpu_serve_shed_total{app,reason}`."""

    def __init__(self, message: str, reason: str = "overload",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s

    def __reduce__(self):
        # Default exception pickling replays __init__ with self.args (the
        # message only), silently resetting reason/retry_after_s to their
        # defaults — a replica-raised batch_queue shed would reach the
        # proxy as a generic "overload" with Retry-After 1.
        return (
            type(self),
            (str(self), self.reason, self.retry_after_s),
        )


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 10
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 10.0
    # SLO-aware scaling: when set, the controller also scales UP whenever the
    # router-reported route-wait p95 (the PR 2 histogram's windowed signal)
    # exceeds this for upscale_delay_s (hysteresis), and only scales DOWN
    # when the p95 sits below half of it — queue depth alone can look calm
    # while per-request latency is collapsing (slow replicas, big batches).
    target_route_wait_p95_s: Optional[float] = None

    def __post_init__(self):
        if not (0 < self.min_replicas <= self.max_replicas):
            raise ValueError("need 0 < min_replicas <= max_replicas")
        if self.target_route_wait_p95_s is not None and (
            self.target_route_wait_p95_s <= 0
        ):
            raise ValueError("target_route_wait_p95_s must be > 0")


@dataclass
class DeploymentInfo:
    name: str
    blob: bytes  # cloudpickled user class/function
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)
    num_replicas: int = 1
    # In-flight calls one replica accepts concurrently (reference
    # `max_concurrent_queries`, default 100 there). Default 1 keeps the
    # strict one-at-a-time replica; raise it to overlap requests — required
    # for `@serve.batch` to ever see a second item.
    max_concurrent_queries: int = 1
    # Per-app admission cap at EACH HTTP proxy: admitted-but-unfinished
    # requests beyond this shed with 503 + Retry-After. 0 = use the global
    # `serve_queue_cap_default` config knob; negative disables for this app.
    max_queued_requests: int = 0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    route_prefix: Optional[str] = None
    is_ingress: bool = False
    # True when the target class carries an ASGI app (@serve.ingress): the
    # proxy speaks ASGI to its replicas instead of the ProxyRequest protocol.
    is_asgi: bool = False
    version: int = 0


@dataclass
class ReplicaInfo:
    replica_id: str
    actor_id: Any  # ActorID — picklable
    deployment: str
    # Copied from the deployment so the ROUTER can cap per-replica load
    # decisions (affinity escape) without a controller round trip.
    max_concurrent_queries: int = 1
    # Controller-driven lifecycle (lifecycle.LIFECYCLE_SPEC "serve_replica"):
    # STARTING -> RUNNING -> DRAINING -> STOPPED.
    state: str = "STARTING"


@dataclass
class ProxyInfo:
    """A controller-managed HTTP proxy (one per node under EveryNode)."""

    proxy_id: str
    actor_id: Any  # ActorID — picklable
    node_id: str
    port: Optional[int] = None
    actor_name: str = ""
    # Controller-driven lifecycle (lifecycle.LIFECYCLE_SPEC "serve_proxy").
    state: str = "STARTING"
