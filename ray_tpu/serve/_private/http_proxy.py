"""HTTP proxy: the HTTP front door, one actor (per node at scale).

Reference: `python/ray/serve/_private/http_proxy.py:250` (`HTTPProxy`, served
by uvicorn at `:434`). Here the server is aiohttp running on a background
thread inside the proxy actor; each request resolves its route by longest
prefix match against the controller's route table (cached), then hops to a
replica through the same Router/power-of-two path as Python handles, with
the blocking result fetch pushed onto the loop's executor.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class ProxyRequest:
    """What a deployment's __call__ receives for an HTTP request."""

    method: str
    path: str  # path with the route prefix stripped
    full_path: str
    query_params: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode()


def _asgi_route_kwargs(request) -> Dict[str, Any]:
    """Routing metadata for ASGI calls: the multiplexed model id (if any)
    rides a reserved kwarg so the router can apply model affinity; route()
    pops it before invoking the replica method."""
    from ray_tpu.serve.multiplex import MODEL_ID_HEADER, MODEL_ID_KWARG

    mid = request.headers.get(MODEL_ID_HEADER, "")
    return {MODEL_ID_KWARG: mid} if mid else {}


class HTTPProxy:
    def __init__(self, controller, port: Optional[int] = None):
        self._controller = controller
        self._handles: Dict[str, Any] = {}
        self._routes: Dict[str, str] = {}
        self._routes_fetched = 0.0
        self._port: Optional[int] = None
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._start_error: Optional[str] = None
        self._bind_error: Optional[str] = None
        self._routes_thread_started = False
        if port is not None:
            # Bind during creation so a crash-restart (max_restarts replays
            # the creation task) comes back LISTENING on the same port — the
            # reference's controller reconciles dead proxies back up the
            # same way (`_private/http_state.py`). A bind failure (port in
            # use) is RECORDED, not raised: raising would fail the creation
            # and restart-loop forever; port() surfaces the error instead.
            try:
                self.start(port=port)
            except Exception as e:  # noqa: BLE001
                self._start_error = repr(e)
                # The common cause during a crash-restart is the dead
                # proxy's socket still draining: keep retrying the SAME
                # port in the background instead of sitting dead forever.
                threading.Thread(
                    target=self._retry_bind, args=(port,), daemon=True,
                    name="proxy-rebind",
                ).start()

    def _retry_bind(self, port: int) -> None:
        import time

        deadline = time.time() + 120
        while time.time() < deadline:
            time.sleep(2.0)
            try:
                self.start(port=port)
                self._start_error = None
                return
            except Exception as e:  # noqa: BLE001
                self._start_error = repr(e)

    def start_error(self):
        return self._start_error

    def pid(self) -> int:
        """Worker pid (health checks + chaos tests)."""
        import os

        return os.getpid()

    # -------------------------------------------------------------- lifecycle
    def start(self, host: str = "127.0.0.1", port: int = 8000) -> int:
        """Start serving; returns the bound port (0 picks a free one)."""
        t = threading.Thread(
            target=self._serve_thread, args=(host, port), daemon=True, name="http"
        )
        t.start()
        # Wait for bind FIRST: a failed bind must raise promptly (the serve
        # thread signals failure) and must not leak a routes-listen long-poll
        # thread per attempt — retry loops would stack immortal pollers.
        # Deadline-bounded: a serve thread that hangs before bind (e.g. in
        # runner.setup()) without recording an error must not block the
        # caller (actor creation) forever.
        deadline = time.monotonic() + 60.0
        while not self._started.wait(timeout=0.2):
            if self._bind_error is not None:
                err, self._bind_error = self._bind_error, None
                raise RuntimeError(f"HTTP proxy failed to bind: {err}")
            if not t.is_alive():
                raise RuntimeError("HTTP proxy serve thread died before binding")
            if time.monotonic() > deadline:
                raise RuntimeError("HTTP proxy did not bind within 60s")
        if not self._routes_thread_started:
            self._routes_thread_started = True
            threading.Thread(
                target=self._routes_listen_loop, daemon=True, name="routes-listen"
            ).start()
        return self._port

    def port(self) -> Optional[int]:
        return self._port

    def _serve_thread(self, host: str, port: int):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        try:
            loop.run_until_complete(site.start())
        except Exception as e:  # noqa: BLE001 — surfaced by start()'s wait loop
            self._bind_error = repr(e)
            return
        self._port = site._server.sockets[0].getsockname()[1]
        self._started.set()
        loop.run_forever()

    # ---------------------------------------------------------------- routing
    def _routes_listen_loop(self):
        """Park in the controller's long poll for route-table pushes (client
        half of the reference's LongPollHost)."""
        import time

        import ray_tpu

        version = -1
        failures = 0
        while True:
            try:
                updates = ray_tpu.get(
                    self._controller.listen_for_change.remote({"routes": version}),
                    timeout=60,
                )
                failures = 0
            except Exception:
                failures += 1
                if failures >= 6:
                    return  # controller gone; fallback fetch path takes over
                time.sleep(0.5)
                continue
            if "routes" in updates:
                version, routes = updates["routes"]
                self._routes = routes

    def _refresh_routes(self) -> None:
        """Pull the route table directly from the controller (the long-poll
        push keeps it fresh in steady state; this covers the windows)."""
        import ray_tpu

        self._routes = ray_tpu.get(self._controller.get_routes.remote())
        self._routes_fetched = time.time()

    def has_route(self, prefix: str) -> bool:
        """True once this proxy's route table includes `prefix`. serve.run's
        readiness barrier polls this so it never returns before every proxy
        can route the new app (reference: serve.run blocks until replicas AND
        routes are ready, `serve/api.py:460`). Misses fall through to a direct
        controller fetch so readiness doesn't wait a long-poll round trip."""
        if prefix in self._routes:
            return True
        try:
            self._refresh_routes()
        except Exception:
            return False
        return prefix in self._routes

    def _route_table(self) -> Dict[str, str]:

        # Push keeps this fresh; the fallback fetch covers the pre-first-push
        # window, rate-limited so a legitimately empty table (no routed
        # deployments) doesn't turn every 404 into a controller round trip.
        if not self._routes and time.time() - self._routes_fetched > 2.0:
            self._refresh_routes()
        return self._routes

    def _match(self, path: str) -> Optional[Tuple[str, bool, str]]:
        match = self._match_in(path, self._route_table())
        if match is None:
            # Miss may be push lag for a just-deployed route: refetch once,
            # rate-limited so real 404 traffic can't hammer the controller.
            if time.time() - self._routes_fetched > 0.5:
                try:
                    self._refresh_routes()
                    match = self._match_in(path, self._routes)
                except Exception:
                    pass
        return match

    @staticmethod
    def _match_in(path: str, routes) -> Optional[Tuple[str, bool, str]]:
        best = None
        for prefix, (dep, is_asgi) in routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, dep, is_asgi)
        if best is None:
            return None
        rest = path[len(best[0]):] or "/"
        return best[1], best[2], rest

    def _handle_for(self, dep: str):
        handle = self._handles.get(dep)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(dep, self._controller)
            self._handles[dep] = handle
        return handle

    async def _handle(self, request):
        from aiohttp import web

        match = self._match(request.path)
        if match is None:
            return web.json_response(
                {"error": f"no route for {request.path}"}, status=404
            )
        dep, is_asgi, rest = match
        body = await request.read()
        handle = self._handle_for(dep)
        try:
            if is_asgi:
                return await self._handle_asgi(request, handle, rest, body)
            return await self._handle_plain(request, handle, rest, body)
        except Exception as e:  # noqa: BLE001 — surface as a 500
            return web.json_response({"error": str(e)}, status=500)

    async def _handle_plain(self, request, handle, rest: str, body: bytes):
        """Non-ASGI deployment: one streaming call; a generator return
        streams as a chunked response, a plain return answers normally."""
        from aiohttp import web

        from ray_tpu.serve.handle import _ReplicaStream

        preq = ProxyRequest(
            method=request.method,
            path=rest,
            full_path=request.path,
            query_params=dict(request.query),
            headers=dict(request.headers),
            body=body,
        )
        call_kwargs = _asgi_route_kwargs(request)
        loop = asyncio.get_event_loop()
        stream = _ReplicaStream(
            handle._ensure_router(), "__call__", (preq,), call_kwargs
        )
        resp = None
        try:
            first = await loop.run_in_executor(None, stream.next_or_none)
            if first is None:
                return web.Response(status=204)
            kind, value = first
            if kind == "single":
                return self._to_response(value)
            # Generator deployment: chunked transfer, one chunk per yield.
            resp = web.StreamResponse()
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            ev = first
            while ev is not None:
                await resp.write(self._to_chunk(ev[1]))
                ev = await loop.run_in_executor(None, stream.next_or_none)
            await resp.write_eof()
            return resp
        except Exception as e:  # noqa: BLE001
            # After prepare() the status line is on the wire: no second
            # response is possible — drop the connection mid-stream instead.
            if resp is None:
                return web.json_response({"error": str(e)}, status=500)
            return resp
        finally:
            stream.close()  # releases unconsumed items + router load unit

    async def _handle_asgi(self, request, handle, rest: str, body: bytes):
        """ASGI ingress: speak ASGI to the replica over a streaming call and
        relay response events as they arrive (SSE/chunked stream end-to-end)."""
        from aiohttp import web

        from ray_tpu.serve.handle import _ReplicaStream

        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method,
            "path": rest,
            "raw_path": request.raw_path.encode(),
            "root_path": "",
            "query_string": request.query_string.encode(),
            "headers": [(k.lower(), v) for k, v in request.headers.items()],
            "client": (request.remote, 0),
            "server": ("127.0.0.1", self._port),
        }
        loop = asyncio.get_event_loop()
        stream = _ReplicaStream(
            handle._ensure_router(), "handle_asgi", (scope, body),
            _asgi_route_kwargs(request),
            raw_method=True,
        )
        resp = None
        try:
            ev = await loop.run_in_executor(None, stream.next_or_none)
            while ev is not None:
                etype = ev.get("type")
                if etype == "http.response.start":
                    resp = web.StreamResponse(status=ev.get("status", 200))
                    for hk, hv in ev.get("headers", []):
                        k = hk.decode() if isinstance(hk, bytes) else hk
                        v = hv.decode() if isinstance(hv, bytes) else hv
                        if k.lower() not in ("content-length", "transfer-encoding"):
                            resp.headers[k] = v
                    resp.enable_chunked_encoding()
                    await resp.prepare(request)
                elif etype == "http.response.body":
                    if resp is None:
                        resp = web.StreamResponse()
                        resp.enable_chunked_encoding()
                        await resp.prepare(request)
                    chunk = ev.get("body", b"")
                    if chunk:
                        await resp.write(chunk)
                elif etype == "asgi.error":
                    if resp is None:
                        return web.json_response({"error": ev["error"]}, status=500)
                    break
                ev = await loop.run_in_executor(None, stream.next_or_none)
            if resp is None:
                return web.Response(status=204)
            await resp.write_eof()
            return resp
        except Exception as e:  # noqa: BLE001
            if resp is None:
                return web.json_response({"error": str(e)}, status=500)
            return resp  # mid-stream failure: connection ends where it stopped
        finally:
            stream.close()

    @staticmethod
    def _to_chunk(value) -> bytes:
        if isinstance(value, bytes):
            return value
        if isinstance(value, str):
            return value.encode()
        return (json.dumps(value) + "\n").encode()

    @staticmethod
    def _to_response(result):
        from aiohttp import web

        if isinstance(result, web.Response):
            return result
        if isinstance(result, bytes):
            return web.Response(body=result)
        if isinstance(result, str):
            return web.Response(text=result)
        try:
            return web.json_response(result)
        except TypeError:
            return web.Response(text=str(result))
