"""HTTP proxy: the HTTP front door, one actor per node at scale.

Reference: `python/ray/serve/_private/http_proxy.py:250` (`HTTPProxy`, served
by uvicorn at `:434`) + `http_state.py` (the controller-managed per-node
proxy fleet). Here the server is aiohttp running on a background thread
inside the proxy actor; each request resolves its route by longest prefix
match against the controller's route table (cached), then hops to a replica
through the same Router/power-of-two path as Python handles, with the
blocking result fetch pushed onto the loop's executor.

Admission control: each app has a per-proxy cap on admitted-but-unfinished
requests (deployment option `max_queued_requests`, default
`serve_queue_cap_default`); beyond it the proxy answers a FAST
`503 + Retry-After` (counted in `ray_tpu_serve_shed_total{app,reason}`)
instead of queueing toward collapse. A draining proxy (serve_drain tag, or
controller drain_proxy) sheds everything new, withdraws from the head's
service directory, and finishes its in-flight window.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ray_tpu.serve._private.common import RequestShedded
from ray_tpu.util import tracing


@dataclass
class ProxyRequest:
    """What a deployment's __call__ receives for an HTTP request."""

    method: str
    path: str  # path with the route prefix stripped
    full_path: str
    query_params: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode()


def _asgi_route_kwargs(request) -> Dict[str, Any]:
    """Routing metadata for ASGI calls: the multiplexed model id (if any)
    rides a reserved kwarg so the router can apply model affinity; route()
    pops it before invoking the replica method."""
    from ray_tpu.serve.multiplex import MODEL_ID_HEADER, MODEL_ID_KWARG

    mid = request.headers.get(MODEL_ID_HEADER, "")
    return {MODEL_ID_KWARG: mid} if mid else {}


def _ingress_metrics():
    """Front-door metric set, or None when enable_metrics is off."""
    from ray_tpu._private import telemetry

    return (
        telemetry.serve_ingress_metrics()
        if telemetry.metrics_enabled() else None
    )


class HTTPProxy:
    def __init__(self, controller, port: Optional[int] = None,
                 proxy_id: Optional[str] = None):
        self._controller = controller
        # Controller-assigned identity (EveryNode fleet): the service
        # directory and the controller's proxy registry then share ONE
        # proxy_id, so the two /api/serve views join on it, not on ports.
        self._proxy_id = proxy_id
        self._handles: Dict[str, Any] = {}
        self._routes: Dict[str, str] = {}
        self._routes_fetched = 0.0
        self._port: Optional[int] = None
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._start_error: Optional[str] = None
        self._bind_error: Optional[str] = None
        self._routes_thread_started = False
        # ---- admission control / drain state ----
        # deployment -> per-proxy cap on admitted-but-unfinished requests
        # (pushed with the route table; 0 = uncapped).
        self._app_caps: Dict[str, int] = {}
        self._ingress_lock = threading.Lock()
        self._app_inflight: Dict[str, int] = {}
        self._app_shed: Dict[str, int] = {}
        self._app_requests: Dict[str, int] = {}
        self._total_inflight = 0
        self._draining = False
        self._announced_id: Optional[str] = None
        if port is not None:
            # Bind during creation so a crash-restart (max_restarts replays
            # the creation task) comes back LISTENING on the same port — the
            # reference's controller reconciles dead proxies back up the
            # same way (`_private/http_state.py`). A bind failure (port in
            # use) is RECORDED, not raised: raising would fail the creation
            # and restart-loop forever; port() surfaces the error instead.
            try:
                self.start(port=port)
            except Exception as e:  # noqa: BLE001
                self._start_error = repr(e)
                # The common cause during a crash-restart is the dead
                # proxy's socket still draining: keep retrying the SAME
                # port in the background instead of sitting dead forever.
                threading.Thread(
                    target=self._retry_bind, args=(port,), daemon=True,
                    name="proxy-rebind",
                ).start()

    def _retry_bind(self, port: int) -> None:
        import time

        deadline = time.time() + 120
        while time.time() < deadline:
            time.sleep(2.0)
            try:
                self.start(port=port)
                self._start_error = None
                return
            except Exception as e:  # noqa: BLE001
                self._start_error = repr(e)

    def start_error(self):
        return self._start_error

    def pid(self) -> int:
        """Worker pid (health checks + chaos tests)."""
        import os

        return os.getpid()

    # -------------------------------------------------------------- lifecycle
    def start(self, host: str = "127.0.0.1", port: int = 8000) -> int:
        """Start serving; returns the bound port (0 picks a free one).
        Idempotent on a LIVE listener: concurrent starters (the controller's
        ensure_proxies racing its reconcile tick) must not stack a second
        HTTP server inside the actor."""
        if self._port is not None:
            return self._port
        t = threading.Thread(
            target=self._serve_thread, args=(host, port), daemon=True, name="http"
        )
        t.start()
        # Wait for bind FIRST: a failed bind must raise promptly (the serve
        # thread signals failure) and must not leak a routes-listen long-poll
        # thread per attempt — retry loops would stack immortal pollers.
        # Deadline-bounded: a serve thread that hangs before bind (e.g. in
        # runner.setup()) without recording an error must not block the
        # caller (actor creation) forever.
        deadline = time.monotonic() + 60.0
        while not self._started.wait(timeout=0.2):
            if self._bind_error is not None:
                err, self._bind_error = self._bind_error, None
                raise RuntimeError(f"HTTP proxy failed to bind: {err}")
            if not t.is_alive():
                raise RuntimeError("HTTP proxy serve thread died before binding")
            if time.monotonic() > deadline:
                raise RuntimeError("HTTP proxy did not bind within 60s")
        if not self._routes_thread_started:
            self._routes_thread_started = True
            threading.Thread(
                target=self._routes_listen_loop, daemon=True, name="routes-listen"
            ).start()
        self._announce()
        return self._port

    def _announce(self) -> None:
        """Register this proxy's listener in the head's service directory
        (serve_proxy_up tag; no-op outside a worker process)."""
        import os

        from ray_tpu._private import worker_main

        proxy_id = self._proxy_id or f"proxy-{os.getpid()}-{self._port}"
        if worker_main.announce_serve_proxy(
            {"proxy_id": proxy_id, "port": self._port, "pid": os.getpid()}
        ):
            self._announced_id = proxy_id

    # ------------------------------------------------------------------ drain
    def _serve_begin_drain(self) -> None:
        """Out-of-band drain hook (worker reader thread, serve_drain tag):
        stop accepting — every new request sheds 503 + Retry-After — and
        withdraw from the service directory; in-flight requests finish."""
        self._draining = True
        if self._announced_id is not None:
            from ray_tpu._private import worker_main

            worker_main.withdraw_serve_proxy(self._announced_id)
            self._announced_id = None

    def _serve_inflight(self) -> int:
        return self._total_inflight

    def prepare_drain(self) -> int:
        """Actor-call form of the drain flag (tests/tooling)."""
        self._serve_begin_drain()
        return self._total_inflight

    def ingress_stats(self) -> Dict[str, Any]:
        """Live per-app admission counters (dashboard /api/serve)."""
        with self._ingress_lock:
            apps = {
                dep: {
                    "inflight": self._app_inflight.get(dep, 0),
                    "shed": self._app_shed.get(dep, 0),
                    "requests": self._app_requests.get(dep, 0),
                    "cap": self._app_caps.get(dep, 0),
                }
                for dep in (
                    set(self._app_inflight) | set(self._app_shed)
                    | set(self._app_requests) | set(self._app_caps)
                )
            }
        return {
            "port": self._port,
            "draining": self._draining,
            "total_inflight": self._total_inflight,
            "apps": apps,
        }

    def port(self) -> Optional[int]:
        return self._port

    def _serve_thread(self, host: str, port: int):
        import os

        from aiohttp import web

        from ray_tpu._private.config import get_config

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        # Bounded forwarding pipeline (serve_proxy_max_concurrent): requests
        # over the bound park on the semaphore (cheap coroutines) instead of
        # flooding the executor — the event loop stays responsive, so shed
        # 503s are fast even at 2x saturation.
        bound = int(get_config().serve_proxy_max_concurrent)
        if bound <= 0:
            bound = max(4, 4 * (os.cpu_count() or 1))
        self._forward_slots = asyncio.Semaphore(bound)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        try:
            loop.run_until_complete(site.start())
        except Exception as e:  # noqa: BLE001 — surfaced by start()'s wait loop
            self._bind_error = repr(e)
            return
        self._port = site._server.sockets[0].getsockname()[1]
        self._started.set()
        loop.run_forever()

    # ---------------------------------------------------------------- routing
    def _routes_listen_loop(self):
        """Park in the controller's long poll for route-table AND admission
        cap pushes (client half of the reference's LongPollHost). Every
        proxy mirrors ONE routing table this way — adding a node just adds
        another parked listener."""
        import time

        import ray_tpu

        versions = {"routes": -1, "app_caps": -1}
        failures = 0
        while True:
            try:
                updates = ray_tpu.get(
                    self._controller.listen_for_change.remote(dict(versions)),
                    timeout=60,
                )
                failures = 0
            except Exception:
                failures += 1
                if failures >= 6:
                    return  # controller gone; fallback fetch path takes over
                time.sleep(0.5)
                continue
            if "routes" in updates:
                versions["routes"], routes = updates["routes"]
                self._routes = routes
            if "app_caps" in updates:
                versions["app_caps"], caps = updates["app_caps"]
                self._app_caps = caps

    def _refresh_routes(self) -> None:
        """Pull the route table directly from the controller (the long-poll
        push keeps it fresh in steady state; this covers the windows)."""
        import ray_tpu

        self._routes = ray_tpu.get(self._controller.get_routes.remote())
        try:
            self._app_caps = ray_tpu.get(
                self._controller.get_app_caps.remote()
            )
        except Exception:  # noqa: BLE001 — caps follow on the next push
            pass
        self._routes_fetched = time.time()

    def has_route(self, prefix: str) -> bool:
        """True once this proxy's route table includes `prefix`. serve.run's
        readiness barrier polls this so it never returns before every proxy
        can route the new app (reference: serve.run blocks until replicas AND
        routes are ready, `serve/api.py:460`). Misses fall through to a direct
        controller fetch so readiness doesn't wait a long-poll round trip."""
        if prefix in self._routes:
            return True
        try:
            self._refresh_routes()
        except Exception:
            return False
        return prefix in self._routes

    def _route_table(self) -> Dict[str, str]:

        # Push keeps this fresh; the fallback fetch covers the pre-first-push
        # window, rate-limited so a legitimately empty table (no routed
        # deployments) doesn't turn every 404 into a controller round trip.
        if not self._routes and time.time() - self._routes_fetched > 2.0:
            self._refresh_routes()
        return self._routes

    def _match(self, path: str) -> Optional[Tuple[str, bool, str]]:
        match = self._match_in(path, self._route_table())
        if match is None:
            # Miss may be push lag for a just-deployed route: refetch once,
            # rate-limited so real 404 traffic can't hammer the controller.
            if time.time() - self._routes_fetched > 0.5:
                try:
                    self._refresh_routes()
                    match = self._match_in(path, self._routes)
                except Exception:
                    pass
        return match

    @staticmethod
    def _match_in(path: str, routes) -> Optional[Tuple[str, bool, str]]:
        best = None
        for prefix, (dep, is_asgi) in routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, dep, is_asgi)
        if best is None:
            return None
        rest = path[len(best[0]):] or "/"
        return best[1], best[2], rest

    def _handle_for(self, dep: str):
        handle = self._handles.get(dep)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(dep, self._controller)
            self._handles[dep] = handle
        return handle

    # ------------------------------------------------------ admission control
    @staticmethod
    def _shed_of(exc) -> Optional[RequestShedded]:
        """The RequestShedded behind `exc`, if any: raised directly (router
        inflight cap) or wrapped in a RayTaskError (a shed-aware
        @serve.batch queue inside the replica). The CAUSE wins over the
        outer exception: RayTaskError.as_instanceof_cause builds a derived
        RayTaskError(RequestShedded) whose MRO re-ran RequestShedded's
        __init__ with DEFAULT reason/retry_after_s — only the original
        cause carries the real shed attributes."""
        cause = getattr(exc, "cause", None) or exc.__cause__
        if isinstance(cause, RequestShedded):
            return cause
        if isinstance(exc, RequestShedded):
            return exc
        return None

    def _shed_response(self, app: str, reason: str,
                       retry_after_s: Optional[float] = None,
                       count: bool = True):
        """Fast 503 + Retry-After: overload converts to an explicit backoff
        signal, never a hung connection (shed-not-collapse). `count=False`
        skips the shared shed counter for sheds the ORIGIN already counted
        (the router's replica_inflight raise) — one shed, one count."""
        from aiohttp import web

        if retry_after_s is None:
            from ray_tpu._private.config import get_config

            retry_after_s = get_config().serve_retry_after_s
        with self._ingress_lock:
            self._app_shed[app] = self._app_shed.get(app, 0) + 1
        m = _ingress_metrics() if count else None
        if m is not None:
            m["shed"].inc(1, {"app": app, "reason": reason})
        import math

        # RFC 9110: Retry-After delay-seconds is a non-negative INTEGER —
        # fractional values break conforming clients' parsers. Round up so
        # a sub-second knob still signals a backoff.
        return web.json_response(
            {"error": "shed", "reason": reason, "app": app},
            status=503,
            headers={"Retry-After": str(max(1, math.ceil(retry_after_s)))},
        )

    def _admit(self, dep: str) -> bool:
        """Count one request in, unless the app is at its per-proxy cap."""
        cap = self._app_caps.get(dep, 0)
        with self._ingress_lock:
            inflight = self._app_inflight.get(dep, 0)
            if cap and inflight >= cap:
                return False
            self._app_inflight[dep] = inflight + 1
            self._app_requests[dep] = self._app_requests.get(dep, 0) + 1
            self._total_inflight += 1
        m = _ingress_metrics()
        if m is not None:
            m["proxy_requests"].inc(1, {"app": dep})
            m["proxy_queue_depth"].set(inflight + 1, {"app": dep})
        return True

    def _release(self, dep: str) -> None:
        with self._ingress_lock:
            left = max(0, self._app_inflight.get(dep, 0) - 1)
            self._app_inflight[dep] = left
            self._total_inflight = max(0, self._total_inflight - 1)
        m = _ingress_metrics()
        if m is not None:
            m["proxy_queue_depth"].set(left, {"app": dep})

    async def _handle(self, request):
        from aiohttp import web

        match = self._match(request.path)
        if match is None:
            return web.json_response(
                {"error": f"no route for {request.path}"}, status=404
            )
        dep, is_asgi, rest = match
        # Root span of the end-to-end request trace: the proxy mints it and
        # the context rides the request envelope (route() -> replica submit
        # -> execute -> nested tasks join the SAME trace). Detached (many
        # requests interleave on this event loop) and tail-keep eligible: a
        # request breaching trace_keep_latency_s is flushed even when its
        # trace lost the head-sampling draw.
        root_span = None
        if tracing.is_enabled():
            root_span = tracing.start_span(
                f"request::{dep}", "request",
                attributes={"app": dep, "method": request.method,
                            "path": request.path},
                detached=True, tail_keep=True,
            )
        trace_ctx = tracing.context_of(root_span)
        status = "OK"
        if self._draining:
            tracing.end_span(root_span, "SHED")
            return self._shed_response(dep, "draining")
        if not self._admit(dep):
            tracing.end_span(root_span, "SHED")
            return self._shed_response(dep, "app_queue")
        try:
            body = await request.read()
            handle = self._handle_for(dep)
            try:
                async with self._forward_slots:
                    if is_asgi:
                        return await self._handle_asgi(
                            request, handle, rest, body, trace_ctx
                        )
                    return await self._handle_plain(
                        request, handle, rest, body, trace_ctx
                    )
            except Exception as e:  # noqa: BLE001 — surface as a 500
                shed = self._shed_of(e)
                if shed is not None:
                    status = "SHED"
                    return self._shed_response(
                        dep, shed.reason, shed.retry_after_s,
                        count=shed.reason != "replica_inflight",
                    )
                status = "ERROR"
                return web.json_response({"error": str(e)}, status=500)
        except BaseException:
            # Body-read failure or client disconnect (CancelledError): the
            # request did NOT succeed — its trace must not say OK.
            status = "ERROR"
            raise
        finally:
            self._release(dep)
            tracing.end_span(root_span, status)

    async def _handle_plain(self, request, handle, rest: str, body: bytes,
                            trace_ctx=None):
        """Non-ASGI deployment: one streaming call; a generator return
        streams as a chunked response, a plain return answers normally."""
        from aiohttp import web

        from ray_tpu.serve.handle import _ReplicaStream

        preq = ProxyRequest(
            method=request.method,
            path=rest,
            full_path=request.path,
            query_params=dict(request.query),
            headers=dict(request.headers),
            body=body,
        )
        call_kwargs = _asgi_route_kwargs(request)
        loop = asyncio.get_event_loop()
        stream = _ReplicaStream(
            handle._ensure_router(), "__call__", (preq,), call_kwargs,
            trace_ctx=trace_ctx,
        )
        resp = None
        try:
            first = await loop.run_in_executor(None, stream.next_or_none)
            if first is None:
                return web.Response(status=204)
            kind, value = first
            if kind == "single":
                return self._to_response(value)
            # Generator deployment: chunked transfer, one chunk per yield.
            resp = web.StreamResponse()
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            ev = first
            while ev is not None:
                await resp.write(self._to_chunk(ev[1]))
                ev = await loop.run_in_executor(None, stream.next_or_none)
            await resp.write_eof()
            return resp
        except Exception:  # noqa: BLE001
            # After prepare() the status line is on the wire: no second
            # response is possible — drop the connection mid-stream instead.
            # Pre-prepare failures re-raise so _handle classifies them
            # (shed -> 503 + Retry-After, anything else -> 500).
            if resp is None:
                raise
            return resp
        finally:
            stream.close()  # releases unconsumed items + router load unit

    async def _handle_asgi(self, request, handle, rest: str, body: bytes,
                           trace_ctx=None):
        """ASGI ingress: speak ASGI to the replica over a streaming call and
        relay response events as they arrive (SSE/chunked stream end-to-end)."""
        from aiohttp import web

        from ray_tpu.serve.handle import _ReplicaStream

        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method,
            "path": rest,
            "raw_path": request.raw_path.encode(),
            "root_path": "",
            "query_string": request.query_string.encode(),
            "headers": [(k.lower(), v) for k, v in request.headers.items()],
            "client": (request.remote, 0),
            "server": ("127.0.0.1", self._port),
        }
        loop = asyncio.get_event_loop()
        stream = _ReplicaStream(
            handle._ensure_router(), "handle_asgi", (scope, body),
            _asgi_route_kwargs(request),
            raw_method=True, trace_ctx=trace_ctx,
        )
        resp = None
        try:
            ev = await loop.run_in_executor(None, stream.next_or_none)
            while ev is not None:
                etype = ev.get("type")
                if etype == "http.response.start":
                    resp = web.StreamResponse(status=ev.get("status", 200))
                    for hk, hv in ev.get("headers", []):
                        k = hk.decode() if isinstance(hk, bytes) else hk
                        v = hv.decode() if isinstance(hv, bytes) else hv
                        if k.lower() not in ("content-length", "transfer-encoding"):
                            resp.headers[k] = v
                    resp.enable_chunked_encoding()
                    await resp.prepare(request)
                elif etype == "http.response.body":
                    if resp is None:
                        resp = web.StreamResponse()
                        resp.enable_chunked_encoding()
                        await resp.prepare(request)
                    chunk = ev.get("body", b"")
                    if chunk:
                        await resp.write(chunk)
                elif etype == "asgi.error":
                    if resp is None:
                        return web.json_response({"error": ev["error"]}, status=500)
                    break
                ev = await loop.run_in_executor(None, stream.next_or_none)
            if resp is None:
                return web.Response(status=204)
            await resp.write_eof()
            return resp
        except Exception:  # noqa: BLE001
            if resp is None:
                raise  # _handle classifies: shed -> 503, else 500
            return resp  # mid-stream failure: connection ends where it stopped
        finally:
            stream.close()

    @staticmethod
    def _to_chunk(value) -> bytes:
        if isinstance(value, bytes):
            return value
        if isinstance(value, str):
            return value.encode()
        return (json.dumps(value) + "\n").encode()

    @staticmethod
    def _to_response(result):
        from aiohttp import web

        if isinstance(result, web.Response):
            return result
        if isinstance(result, bytes):
            return web.Response(body=result)
        if isinstance(result, str):
            return web.Response(text=result)
        try:
            return web.json_response(result)
        except TypeError:
            return web.Response(text=str(result))
