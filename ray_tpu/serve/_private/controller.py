"""ServeController: the reconciling control plane of Serve.

Reference: `python/ray/serve/_private/controller.py:73` (`ServeController`)
+ `deployment_state.py:1009` (`DeploymentState` reconciler) +
`_private/long_poll.py:185` (`LongPollHost`) + `http_state.py` (per-node
proxy management) + `autoscaling_policy.py`.
One named actor holds the desired state (deployments -> replica sets, plus
the per-node HTTP proxy fleet), starts/stops replica AND proxy actors to
match, PUSHES routing tables / app admission caps / the proxy set to
routers and proxies via key-versioned long polls (`listen_for_change` —
callers block in a threaded-actor slot until a watched key's version
moves), and runs the autoscaling loop off router-reported load and the
route-wait p95 SLO signal.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import lifecycle
from ray_tpu._private.events import emit_event
from ray_tpu.serve._private.common import (
    PROXY_NAME,
    DeploymentInfo,
    ProxyInfo,
    ReplicaInfo,
)

# Long-poll keys: f"replicas::{deployment}", ROUTES_KEY, CAPS_KEY. (The
# proxy FLEET is pull-based — get_proxies / the head's service directory —
# so there is deliberately no long-poll key for it.)
ROUTES_KEY = "routes"
CAPS_KEY = "app_caps"
# Server-side re-arm bound: a poll with no change returns {} after this long
# and the client immediately re-polls (keeps slots from being held forever).
LISTEN_TIMEOUT_S = 20.0
# Cancelled-listener set bound: ids whose listener already unparked (timeout
# race) would otherwise pin a set entry forever.
_MAX_CANCELLED = 1024


class ServeController:
    """Deploy with max_concurrency: long-polling routers each occupy one call
    slot while they wait."""

    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._replicas: Dict[str, List[ReplicaInfo]] = {}
        self._replica_counter = 0
        # route_prefix -> (deployment name, is_asgi)
        self._routes: Dict[str, tuple] = {}
        # deployment -> resolved per-proxy admission cap (0 = uncapped).
        self._app_caps: Dict[str, int] = {}
        # node_id -> ProxyInfo for controller-managed per-node proxies.
        self._proxies: Dict[str, ProxyInfo] = {}
        self._proxy_location: Optional[str] = None
        self._proxy_port = 0
        # Nodes cordoned off ingress (drain_proxy): the reconcile loop must
        # not re-adopt the still-alive draining actor (nor respawn one) —
        # a later ensure_proxies() lifts the cordon.
        self._proxy_cordoned: set = set()
        self._self_handle = None
        self._last_proxy_reconcile = 0.0
        # deployment -> {router_id -> (inflight, timestamp, route_wait_p95)}
        self._load: Dict[str, Dict[str, Any]] = {}
        self._downscale_since: Dict[str, Optional[float]] = {}
        self._slo_violation_since: Dict[str, Optional[float]] = {}
        self._lock = threading.RLock()
        # Serializes proxy reconciliation passes (ensure_proxies vs the
        # control loop's tick): NOT self._lock — reconciliation does
        # blocking actor calls and must never hold the long-poll lock.
        self._proxy_reconcile_lock = threading.Lock()
        self._change = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        # Long-poll listener bookkeeping: parked call count (leak regression
        # tests read it) + cancelled listener ids (a GC'd router's __del__
        # unparks its listener so controller call slots recycle promptly).
        self._parked_listeners = 0
        self._cancelled_listeners: Dict[str, None] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-controller"
        )
        self._thread.start()

    # ------------------------------------------------------------- long poll
    def _bump(self, key: str) -> None:
        """Record a change under `key` and wake blocked listeners (must hold
        self._lock)."""
        self._versions[key] = self._versions.get(key, 0) + 1
        self._change.notify_all()

    def _snapshot(self, key: str):
        if key == ROUTES_KEY:
            return dict(self._routes)
        if key == CAPS_KEY:
            return dict(self._app_caps)
        if key.startswith("replicas::"):
            return list(self._replicas.get(key[len("replicas::"):], []))
        return None

    def listen_for_change(self, known: Dict[str, int],
                          listener_id: Optional[str] = None) -> Dict[str, Any]:
        """Block until any watched key's version differs from the caller's,
        then return {key: (version, snapshot)} for the changed keys; {} on
        server-side timeout (client re-arms) or when the listener was
        cancelled (its router was closed/GC'd — the slot must come back).
        The push half of the reference's LongPollHost (`long_poll.py:185`)."""
        deadline = time.time() + LISTEN_TIMEOUT_S
        with self._change:
            self._parked_listeners += 1
            try:
                while True:
                    if (
                        listener_id is not None
                        and listener_id in self._cancelled_listeners
                    ):
                        del self._cancelled_listeners[listener_id]
                        return {}
                    changed = {
                        k: (self._versions.get(k, 0), self._snapshot(k))
                        for k, v in known.items()
                        if self._versions.get(k, 0) != v
                    }
                    if changed:
                        return changed
                    remaining = deadline - time.time()
                    if remaining <= 0 or self._stop.is_set():
                        return {}
                    self._change.wait(remaining)
            finally:
                self._parked_listeners -= 1

    def cancel_listener(self, listener_id: str) -> None:
        """Unpark (and retire) one listener by id — called by Router.close /
        __del__ so a deleted handle's long-poll slot frees immediately
        instead of leaking across app redeploys."""
        with self._change:
            self._cancelled_listeners[listener_id] = None
            while len(self._cancelled_listeners) > _MAX_CANCELLED:
                self._cancelled_listeners.pop(
                    next(iter(self._cancelled_listeners))
                )
            self._change.notify_all()

    def listener_count(self) -> int:
        """Currently-parked listen_for_change calls (leak regression gauge)."""
        with self._lock:
            return self._parked_listeners

    # ------------------------------------------------------------- deployment
    def _resolve_cap(self, info: DeploymentInfo) -> int:
        """Per-proxy admission cap for one app: option > 0 wins, 0 defers to
        the serve_queue_cap_default knob, negative disables (0 out)."""
        from ray_tpu._private.config import get_config

        raw = int(getattr(info, "max_queued_requests", 0))
        if raw > 0:
            return raw
        if raw < 0:
            return 0
        return max(0, int(get_config().serve_queue_cap_default))

    def deploy(self, info: DeploymentInfo) -> None:
        with self._lock:
            existing = self._deployments.get(info.name)
            if existing is not None:
                info.version = existing.version + 1
            self._deployments[info.name] = info
            self._app_caps[info.name] = self._resolve_cap(info)
            self._bump(CAPS_KEY)
            if info.route_prefix:
                self._routes[info.route_prefix] = (info.name, info.is_asgi)
                self._bump(ROUTES_KEY)
            if info.autoscaling_config:
                target = max(
                    info.autoscaling_config.min_replicas,
                    min(info.num_replicas, info.autoscaling_config.max_replicas),
                )
            else:
                target = info.num_replicas
            if existing is not None:
                # Redeploy: replace existing replicas with the new version.
                # The old set drains in the background (graceful) while the
                # new set comes up — routers already stopped sending to it.
                self._scale_to(info.name, 0)
            self._scale_to(info.name, target)
            version = info.version
        # Emit OUTSIDE the lock: the event append is a blocking control-plane
        # round trip and long-poll listeners share self._lock.
        # deploy() runs as an actor call, so the executing worker's job_id is
        # the CALLING driver's (worker_main sets it per task): riding it on
        # the event is what lets the head's JobLedger attribute this app's
        # proxy request counters to the deploying tenant — no new wire tag.
        from ray_tpu._private.worker import global_worker

        job = global_worker.job_id.hex() if global_worker.job_id else None
        emit_event(
            "serve_deploy",
            f"app {info.name} v{version} deployed "
            f"({target} replica(s), route {info.route_prefix or '-'})",
            source="serve-controller", app=info.name, version=version,
            replicas=target, job=job,
        )

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            self._scale_to(name, 0)
            self._deployments.pop(name, None)
            self._replicas.pop(name, None)
            self._load.pop(name, None)
            self._app_caps.pop(name, None)
            # Hysteresis clocks die with the app: a same-name redeploy must
            # not inherit a minutes-old violation/downscale timestamp.
            self._slo_violation_since.pop(name, None)
            self._downscale_since.pop(name, None)
            self._routes = {p: d for p, d in self._routes.items() if d[0] != name}
            self._bump(ROUTES_KEY)
            self._bump(CAPS_KEY)
            self._bump(f"replicas::{name}")
        emit_event("serve_delete", f"app {name} deleted",
                   source="serve-controller", app=name)

    def _scale_to(self, name: str, target: int, drain: bool = True) -> None:
        import ray_tpu
        from ray_tpu._private import retry
        from ray_tpu._private.config import get_config
        from ray_tpu.serve._private.replica import ServeReplica

        info = self._deployments[name]
        replicas = self._replicas.setdefault(name, [])
        cfg = get_config()
        while len(replicas) < target:
            self._replica_counter += 1
            rid = f"{name}#{self._replica_counter}"
            opts = dict(info.ray_actor_options or {})
            opts.setdefault("num_cpus", 0.1)
            opts["name"] = f"SERVE_REPLICA::{rid}"
            if info.max_concurrent_queries > 1:
                # Threaded replica calls; async user methods share the
                # actor's event loop, where @serve.batch queues live.
                opts["max_concurrency"] = int(info.max_concurrent_queries)

            def _create():
                handle = (
                    ray_tpu.remote(ServeReplica)
                    .options(**opts)
                    .remote(
                        name, info.blob, info.init_args, info.init_kwargs,
                        max_concurrent_queries=info.max_concurrent_queries,
                    )
                )
                # Block until constructed so routing tables only list live
                # replicas.
                ray_tpu.get(handle.__ray_ready__.remote())
                return handle

            # Replica churn rides the unified PR 4 retry policy: a node that
            # just lost capacity (autoscaler/preemption) fails creation for a
            # beat — deterministic backoff instead of a hot failure loop.
            # Sleeps are capped well below the config max: _scale_to runs
            # under self._lock (long-poll listeners share it), so a failing
            # placement must cost milliseconds of lock hold, not seconds.
            handle = retry.call_with_retry(
                _create,
                retry.RetryPolicy(
                    max_attempts=3,
                    base_delay_s=max(0.0, cfg.retry_backoff_base_ms / 1000.0),
                    max_delay_s=0.25,
                ),
            )
            rep = ReplicaInfo(
                rid, handle._actor_id, name,
                max_concurrent_queries=info.max_concurrent_queries,
            )
            # The creation retry loop above succeeded: the actor exists and
            # routers may target it as soon as the table bumps.
            rep.state = lifecycle.step("serve_replica", rep.state, "RUNNING")
            replicas.append(rep)
            self._bump(f"replicas::{name}")
        while len(replicas) > target:
            rep = replicas.pop()
            # Routers stop sending the moment this push lands; the replica
            # then finishes its inflight window before the kill (graceful
            # drain — zero admitted requests dropped).
            self._bump(f"replicas::{name}")
            if drain:
                self._drain_then_kill(rep)
            else:
                self._kill_replica(rep)

    # ----------------------------------------------------------------- drain
    def _drain_then_kill(self, rep: ReplicaInfo) -> None:
        """Background graceful stop: wait out the replica's inflight window
        (scheduler-side count — it sees calls still parked in the actor's
        ordered queue, which the replica itself cannot), then kill."""
        from ray_tpu._private.config import get_config

        timeout_s = float(get_config().serve_drain_timeout_s)
        rep.state = lifecycle.step("serve_replica", rep.state, "DRAINING")

        def drain():
            from ray_tpu._private.worker import global_worker

            ctx = global_worker.context
            deadline = time.monotonic() + timeout_s
            try:
                while time.monotonic() < deadline:
                    left = ctx.serve_actor_inflight(rep.actor_id.binary())
                    if not left:
                        break
                    time.sleep(0.05)
            except Exception:  # noqa: BLE001 — head gone/actor dead: just kill
                pass
            self._kill_replica(rep)

        threading.Thread(
            target=drain, daemon=True, name=f"serve-drain-{rep.replica_id}"
        ).start()

    def _kill_replica(self, rep: ReplicaInfo) -> None:
        import ray_tpu
        from ray_tpu.actor import ActorHandle

        rep.state = lifecycle.step("serve_replica", rep.state, "STOPPED")
        try:
            ray_tpu.kill(ActorHandle(rep.actor_id, "ServeReplica"))
        except Exception:
            pass

    # ----------------------------------------------------------- proxy fleet
    def _own_handle(self):
        """An ActorHandle to THIS controller actor (passed to proxies)."""
        if self._self_handle is None:
            import ray_tpu
            from ray_tpu.actor import ActorHandle
            from ray_tpu.serve._private.common import CONTROLLER_NAME

            h = ray_tpu.get_actor(CONTROLLER_NAME)
            self._self_handle = ActorHandle(h._actor_id, "ServeController")
        return self._self_handle

    def ensure_proxies(self, port: int = 0) -> Dict[str, int]:
        """Reconcile one HTTP proxy actor per alive node (the reference's
        proxy_location="EveryNode", `http_state.py`): spawned/managed here
        exactly like replicas, registered in the head's service directory on
        bind, each mirroring the routing table via the shared long poll.
        Adding a node adds ingress capacity on the next reconcile tick;
        killing a proxy removes one Retry-After target until its restart.
        Returns node_id -> bound port."""
        with self._lock:
            self._proxy_location = "EveryNode"
            self._proxy_port = int(port)
            self._proxy_cordoned.clear()
        self._reconcile_proxies()
        with self._lock:
            return {nid: p.port for nid, p in self._proxies.items()}

    def _reconcile_proxies(self) -> None:
        with self._proxy_reconcile_lock:
            self._reconcile_proxies_locked()

    def _reconcile_proxies_locked(self) -> None:
        import ray_tpu
        from ray_tpu.actor import ActorHandle
        from ray_tpu.serve._private.http_proxy import HTTPProxy
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        with self._lock:
            if self._proxy_location != "EveryNode":
                return
            existing = dict(self._proxies)
            cordoned = set(self._proxy_cordoned)
            want_port = self._proxy_port
        try:
            nodes = ray_tpu.nodes()
        except Exception:  # noqa: BLE001 — head unreachable mid-shutdown
            return
        alive = {
            n["node_id"] for n in nodes
            if n.get("alive", True) and n["node_id"] not in cordoned
        }
        for nid in list(existing):
            if nid not in alive:
                with self._lock:
                    p = self._proxies.pop(nid, None)
                if p is not None:
                    p.state = lifecycle.step("serve_proxy", p.state, "STOPPED")
                existing.pop(nid, None)
        for nid in sorted(alive):
            # Re-check the LIVE cordon set per node: a drain_proxy that
            # lands mid-pass (this loop blocks on actor probes) must not
            # have its node resurrected by the snapshot taken at pass start.
            with self._lock:
                if nid in self._proxy_cordoned:
                    continue
            info = existing.get(nid)
            respawn = False
            if info is not None:
                # Liveness/port probe: a crash-restarted proxy comes back
                # with no listener (EveryNode binds ephemeral ports in
                # start(), not the creation task) — restart it.
                try:
                    h = ActorHandle(info.actor_id, "HTTPProxy")
                    bound = ray_tpu.get(h.port.remote(), timeout=10)
                    if bound is None:
                        bound = ray_tpu.get(
                            h.start.remote(port=want_port), timeout=30
                        )
                    if bound != info.port:
                        info.port = bound
                    continue
                except Exception:  # noqa: BLE001 — actor gone: respawn below
                    respawn = True
                    with self._lock:
                        p = self._proxies.pop(nid, None)
                    if p is not None:
                        p.state = lifecycle.step("serve_proxy", p.state,
                                                 "STOPPED")
            name = f"{PROXY_NAME}::{nid[:8]}"
            proxy_id = f"{name}@{nid[:8]}"
            try:
                handle = (
                    ray_tpu.remote(HTTPProxy)
                    .options(
                        name=name,
                        num_cpus=0.1,
                        get_if_exists=True,
                        lifetime="detached",
                        max_restarts=10,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=nid, soft=False
                        ),
                    )
                    # One identity across the fleet registry AND the head's
                    # service directory: the proxy announces this id on bind.
                    .remote(self._own_handle(), proxy_id=proxy_id)
                )
                # get_if_exists may adopt a proxy another driver started:
                # starting it again would stack a second HTTP server (and
                # start() is idempotent on a live listener regardless).
                # Default want_port=0 binds a free port — required when
                # virtual nodes share one host.
                bound = ray_tpu.get(handle.port.remote(), timeout=30)
                if bound is None:
                    bound = ray_tpu.get(
                        handle.start.remote(port=want_port), timeout=60
                    )
            except Exception:  # noqa: BLE001 — node raced away; next tick
                continue
            with self._lock:
                if nid in self._proxy_cordoned:
                    # Cordoned while we were spawning: registering it would
                    # leak a live announced proxy the drain already decided
                    # to remove — kill it instead.
                    cordon_hit = True
                else:
                    cordon_hit = False
                    p = ProxyInfo(
                        proxy_id=proxy_id,
                        actor_id=handle._actor_id,
                        node_id=nid,
                        port=bound,
                        actor_name=name,
                    )
                    # Bound and probed above: it serves as soon as it is in
                    # the fleet table.
                    p.state = lifecycle.step("serve_proxy", p.state, "RUNNING")
                    self._proxies[nid] = p
            if cordon_hit:
                try:
                    ray_tpu.kill(ActorHandle(handle._actor_id, "HTTPProxy"))
                except Exception:
                    pass
            elif respawn:
                emit_event(
                    "serve_proxy_failover",
                    f"proxy on node {nid[:8]} was dead; respawned on port "
                    f"{bound}",
                    severity="warning", source="serve-controller",
                    node_id=nid, port=bound,
                )

    def get_proxies(self) -> Dict[str, Dict[str, Any]]:
        """node_id -> {actor_id, port, name, proxy_id} for managed proxies."""
        with self._lock:
            return {
                nid: {
                    "actor_id": p.actor_id,
                    "port": p.port,
                    "name": p.actor_name,
                    "proxy_id": p.proxy_id,
                }
                for nid, p in self._proxies.items()
            }

    def drain_proxy(self, node_id: str, timeout_s: Optional[float] = None) -> dict:
        """Gracefully drain one managed proxy over the wire protocol
        (serve_drain tag via the head): it stops accepting (503 +
        Retry-After), withdraws from the service directory, finishes its
        in-flight HTTP requests, then is killed and dropped from the fleet."""
        import ray_tpu
        from ray_tpu._private.config import get_config
        from ray_tpu._private.worker import global_worker
        from ray_tpu.actor import ActorHandle

        if timeout_s is None:
            timeout_s = float(get_config().serve_drain_timeout_s)
        with self._lock:
            p = self._proxies.pop(node_id, None)
            if p is not None:
                # Cordon BEFORE the (slow) drain: the reconcile tick must
                # not re-adopt the still-alive draining actor and push it
                # back to clients mid-drain.
                self._proxy_cordoned.add(node_id)
        if p is None:
            return {"ok": False, "inflight": -1, "error": "no proxy on node"}
        p.state = lifecycle.step("serve_proxy", p.state, "DRAINING")
        result = global_worker.context.serve_drain_actor(
            p.actor_id.binary(), float(timeout_s)
        )
        try:
            ray_tpu.kill(ActorHandle(p.actor_id, "HTTPProxy"))
        except Exception:
            pass
        p.state = lifecycle.step("serve_proxy", p.state, "STOPPED")
        emit_event(
            "serve_proxy_drain",
            f"proxy on node {node_id[:8]} drained and removed "
            f"(inflight at finish: {result.get('inflight')})",
            source="serve-controller", node_id=node_id,
            ok=bool(result.get("ok")),
        )
        return result

    # ---------------------------------------------------------------- routing
    def get_replicas(self, name: str) -> List[ReplicaInfo]:
        with self._lock:
            return list(self._replicas.get(name, []))

    def get_routes(self) -> Dict[str, tuple]:
        """route_prefix -> (deployment_name, is_asgi)."""
        with self._lock:
            return dict(self._routes)

    def get_app_caps(self) -> Dict[str, int]:
        """deployment -> resolved per-proxy admission cap (0 = uncapped)."""
        with self._lock:
            return dict(self._app_caps)

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "num_replicas": len(self._replicas.get(name, [])),
                    "route_prefix": info.route_prefix,
                    "version": info.version,
                    "autoscaling": info.autoscaling_config is not None,
                }
                for name, info in self._deployments.items()
            }

    def ingress_status(self) -> Dict[str, Any]:
        """Apps + replicas + proxy fleet with live queue depth / inflight /
        shed counters (the dashboard's /api/serve payload)."""
        import ray_tpu
        from ray_tpu.actor import ActorHandle

        now = time.time()
        with self._lock:
            apps: Dict[str, Any] = {}
            for name, info in self._deployments.items():
                loads = self._load.get(name, {})
                inflight = sum(
                    v[0] for v in loads.values() if now - v[1] < 5.0
                )
                p95s = [
                    v[2] for v in loads.values()
                    if now - v[1] < 5.0 and len(v) > 2 and v[2] is not None
                ]
                apps[name] = {
                    "route_prefix": info.route_prefix,
                    "version": info.version,
                    "replicas": [
                        r.replica_id for r in self._replicas.get(name, [])
                    ],
                    "max_queued_requests": self._app_caps.get(name, 0),
                    "autoscaling": info.autoscaling_config is not None,
                    "inflight": inflight,
                    "route_wait_p95_s": max(p95s) if p95s else None,
                    "queue_depth": 0,
                    "shed": 0,
                    "requests": 0,
                }
            proxy_infos = dict(self._proxies)
        # Poll every proxy CONCURRENTLY: a sequential loop would make the
        # dashboard's /api/serve degrade linearly with unreachable proxies
        # (N x the per-proxy timeout).
        stats_by_nid: Dict[str, Any] = {}

        def _poll(nid, p):
            try:
                stats_by_nid[nid] = ray_tpu.get(
                    ActorHandle(p.actor_id, "HTTPProxy").ingress_stats.remote(),
                    timeout=2,
                )
            except Exception:  # noqa: BLE001 — mid-restart proxy: listed bare
                pass

        pollers = [
            threading.Thread(target=_poll, args=(nid, p), daemon=True)
            for nid, p in proxy_infos.items()
        ]
        for t in pollers:
            t.start()
        for t in pollers:
            t.join(timeout=5)
        proxies: List[Dict[str, Any]] = []
        for nid, p in proxy_infos.items():
            entry: Dict[str, Any] = {
                "node_id": nid, "port": p.port, "proxy_id": p.proxy_id,
            }
            stats = stats_by_nid.get(nid)
            if stats is None:
                entry["unreachable"] = True
            else:
                entry.update(stats)
                for dep, s in stats.get("apps", {}).items():
                    if dep in apps:
                        apps[dep]["queue_depth"] += s.get("inflight", 0)
                        apps[dep]["shed"] += s.get("shed", 0)
                        apps[dep]["requests"] += s.get("requests", 0)
            proxies.append(entry)
        return {"apps": apps, "proxies": proxies}

    def report_failure(self, name: str, replica_id: str) -> None:
        """Router saw a dead replica: replace it (reference: replica recovery
        in DeploymentState reconciliation)."""
        replaced = False
        with self._lock:
            replicas = self._replicas.get(name, [])
            before = len(replicas)
            for r in replicas:
                if r.replica_id == replica_id:
                    r.state = lifecycle.step("serve_replica", r.state, "STOPPED")
            replicas[:] = [r for r in replicas if r.replica_id != replica_id]
            if len(replicas) < before:
                self._bump(f"replicas::{name}")
                if name in self._deployments:
                    self._scale_to(name, before)
                    replaced = True
        if replaced:
            emit_event(
                "serve_replica_failover",
                f"replica {replica_id} of app {name} died; replacement "
                "started",
                severity="warning", source="serve-controller", app=name,
                replica_id=replica_id,
            )

    # ------------------------------------------------------------ autoscaling
    def report_load(self, name: str, router_id: str, inflight: int,
                    route_wait_p95: Optional[float] = None) -> None:
        with self._lock:
            self._load.setdefault(name, {})[router_id] = (
                inflight, time.time(), route_wait_p95
            )

    def _control_loop(self):
        while not self._stop.wait(0.5):
            try:
                self._autoscale_once()
            except Exception:
                pass
            try:
                now = time.monotonic()
                if now - self._last_proxy_reconcile >= 2.0:
                    self._last_proxy_reconcile = now
                    self._reconcile_proxies()
            except Exception:
                pass

    def _autoscale_once(self):
        now = time.time()
        scaled: List[tuple] = []
        with self._lock:
            for name, info in list(self._deployments.items()):
                cfg = info.autoscaling_config
                if cfg is None:
                    continue
                loads = self._load.get(name, {})
                fresh = [v for v in loads.values() if now - v[1] < 5.0]
                total = sum(v[0] for v in fresh)
                p95s = [
                    v[2] for v in fresh if len(v) > 2 and v[2] is not None
                ]
                p95 = max(p95s) if p95s else None
                cur = len(self._replicas.get(name, []))
                desired = max(
                    cfg.min_replicas,
                    min(
                        cfg.max_replicas,
                        -(-total // max(cfg.target_num_ongoing_requests_per_replica, 1e-9))
                        if total
                        else cfg.min_replicas,
                    ),
                )
                desired = int(desired)
                # SLO pressure: queue depth can look fine while the p95
                # collapses (slow model, deep batches). A sustained
                # violation (hysteresis = upscale_delay_s) forces +1 above
                # the queue-depth answer; a comfortably-met SLO (p95 under
                # half the target) releases the floor so downscale can run.
                slo = cfg.target_route_wait_p95_s
                if slo is not None:
                    if p95 is not None and p95 > slo:
                        since = self._slo_violation_since.get(name)
                        if since is None:
                            self._slo_violation_since[name] = now
                        elif now - since >= cfg.upscale_delay_s:
                            desired = min(cfg.max_replicas, max(desired, cur + 1))
                            self._slo_violation_since[name] = now
                    else:
                        # Met OR no fresh signal (idle): the violation clock
                        # resets — a single violating sample after an idle
                        # gap must not ride a stale timestamp past the
                        # upscale_delay_s hysteresis.
                        self._slo_violation_since[name] = None
                        if p95 is not None and p95 > 0.5 * slo and desired < cur:
                            desired = cur  # hold: SLO met but not by margin
                if desired > cur:
                    self._downscale_since[name] = None
                    self._scale_to(name, desired)
                    scaled.append((name, cur, desired, p95))
                elif desired < cur:
                    since = self._downscale_since.get(name)
                    if since is None:
                        self._downscale_since[name] = now
                    elif now - since >= cfg.downscale_delay_s:
                        self._scale_to(name, desired)
                        self._downscale_since[name] = None
                        scaled.append((name, cur, desired, p95))
                else:
                    self._downscale_since[name] = None
        # Events emitted after the lock drops (the append is a blocking
        # control-plane round trip; long-poll listeners share self._lock).
        for name, cur, desired, p95 in scaled:
            emit_event(
                "serve_scale",
                f"app {name} autoscaled {cur} -> {desired} replica(s)"
                + (f" (route-wait p95 {p95 * 1000:.0f}ms)"
                   if p95 is not None else ""),
                source="serve-controller", app=name,
                replicas_before=cur, replicas_after=desired,
            )

    def shutdown(self) -> None:
        import ray_tpu
        from ray_tpu.actor import ActorHandle

        with self._lock:
            for name in list(self._deployments):
                # Teardown: immediate kills (nothing routes here anymore).
                self._scale_to(name, 0, drain=False)
            self._deployments.clear()
            self._replicas.clear()
            self._routes.clear()
            self._app_caps.clear()
            proxies = list(self._proxies.values())
            self._proxies.clear()
            self._proxy_cordoned.clear()
            self._proxy_location = None
            self._stop.set()
            self._change.notify_all()  # release parked long-polls
        for p in proxies:
            p.state = lifecycle.step("serve_proxy", p.state, "STOPPED")
            try:
                ray_tpu.kill(ActorHandle(p.actor_id, "HTTPProxy"))
            except Exception:
                pass
