"""ServeController: the reconciling control plane of Serve.

Reference: `python/ray/serve/_private/controller.py:73` (`ServeController`)
+ `deployment_state.py:1009` (`DeploymentState` reconciler) +
`_private/long_poll.py:185` (`LongPollHost`) + `autoscaling_policy.py`.
One named actor holds the desired state (deployments -> replica sets),
starts/stops replica actors to match, PUSHES routing tables to routers and
proxies via key-versioned long polls (`listen_for_change` — callers block in a
threaded-actor slot until a watched key's version moves), and runs the
autoscaling loop off router-reported load.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve._private.common import DeploymentInfo, ReplicaInfo

# Long-poll keys: f"replicas::{deployment}" and ROUTES_KEY.
ROUTES_KEY = "routes"
# Server-side re-arm bound: a poll with no change returns {} after this long
# and the client immediately re-polls (keeps slots from being held forever).
LISTEN_TIMEOUT_S = 20.0


class ServeController:
    """Deploy with max_concurrency: long-polling routers each occupy one call
    slot while they wait."""

    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._replicas: Dict[str, List[ReplicaInfo]] = {}
        self._replica_counter = 0
        # route_prefix -> (deployment name, is_asgi)
        self._routes: Dict[str, tuple] = {}
        # deployment -> {router_id -> (inflight, timestamp)}
        self._load: Dict[str, Dict[str, Any]] = {}
        self._downscale_since: Dict[str, Optional[float]] = {}
        self._lock = threading.RLock()
        self._change = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._autoscale_loop, daemon=True, name="serve-autoscaler"
        )
        self._thread.start()

    # ------------------------------------------------------------- long poll
    def _bump(self, key: str) -> None:
        """Record a change under `key` and wake blocked listeners (must hold
        self._lock)."""
        self._versions[key] = self._versions.get(key, 0) + 1
        self._change.notify_all()

    def _snapshot(self, key: str):
        if key == ROUTES_KEY:
            return dict(self._routes)
        if key.startswith("replicas::"):
            return list(self._replicas.get(key[len("replicas::"):], []))
        return None

    def listen_for_change(self, known: Dict[str, int]) -> Dict[str, Any]:
        """Block until any watched key's version differs from the caller's,
        then return {key: (version, snapshot)} for the changed keys; {} on
        server-side timeout (client re-arms). The push half of the reference's
        LongPollHost (`long_poll.py:185`)."""
        deadline = time.time() + LISTEN_TIMEOUT_S
        with self._change:
            while True:
                changed = {
                    k: (self._versions.get(k, 0), self._snapshot(k))
                    for k, v in known.items()
                    if self._versions.get(k, 0) != v
                }
                if changed:
                    return changed
                remaining = deadline - time.time()
                if remaining <= 0 or self._stop.is_set():
                    return {}
                self._change.wait(remaining)

    # ------------------------------------------------------------- deployment
    def deploy(self, info: DeploymentInfo) -> None:
        with self._lock:
            existing = self._deployments.get(info.name)
            if existing is not None:
                info.version = existing.version + 1
            self._deployments[info.name] = info
            if info.route_prefix:
                self._routes[info.route_prefix] = (info.name, info.is_asgi)
                self._bump(ROUTES_KEY)
            if info.autoscaling_config:
                target = max(
                    info.autoscaling_config.min_replicas,
                    min(info.num_replicas, info.autoscaling_config.max_replicas),
                )
            else:
                target = info.num_replicas
            if existing is not None:
                # Redeploy: replace existing replicas with the new version.
                self._scale_to(info.name, 0)
            self._scale_to(info.name, target)

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            self._scale_to(name, 0)
            self._deployments.pop(name, None)
            self._replicas.pop(name, None)
            self._routes = {p: d for p, d in self._routes.items() if d[0] != name}
            self._bump(ROUTES_KEY)
            self._bump(f"replicas::{name}")

    def _scale_to(self, name: str, target: int) -> None:
        import ray_tpu
        from ray_tpu.serve._private.replica import ServeReplica

        info = self._deployments[name]
        replicas = self._replicas.setdefault(name, [])
        while len(replicas) < target:
            self._replica_counter += 1
            rid = f"{name}#{self._replica_counter}"
            opts = dict(info.ray_actor_options or {})
            opts.setdefault("num_cpus", 0.1)
            opts["name"] = f"SERVE_REPLICA::{rid}"
            if info.max_concurrent_queries > 1:
                # Threaded replica calls; async user methods share the
                # actor's event loop, where @serve.batch queues live.
                opts["max_concurrency"] = int(info.max_concurrent_queries)
            handle = (
                ray_tpu.remote(ServeReplica)
                .options(**opts)
                .remote(
                    name, info.blob, info.init_args, info.init_kwargs,
                    max_concurrent_queries=info.max_concurrent_queries,
                )
            )
            # Block until constructed so routing tables only list live replicas.
            ray_tpu.get(handle.__ray_ready__.remote())
            replicas.append(
                ReplicaInfo(
                    rid, handle._actor_id, name,
                    max_concurrent_queries=info.max_concurrent_queries,
                )
            )
            self._bump(f"replicas::{name}")
        while len(replicas) > target:
            rep = replicas.pop()
            self._kill_replica(rep)
            self._bump(f"replicas::{name}")

    def _kill_replica(self, rep: ReplicaInfo) -> None:
        import ray_tpu
        from ray_tpu.actor import ActorHandle

        try:
            ray_tpu.kill(ActorHandle(rep.actor_id, "ServeReplica"))
        except Exception:
            pass

    # ---------------------------------------------------------------- routing
    def get_replicas(self, name: str) -> List[ReplicaInfo]:
        with self._lock:
            return list(self._replicas.get(name, []))

    def get_routes(self) -> Dict[str, tuple]:
        """route_prefix -> (deployment_name, is_asgi)."""
        with self._lock:
            return dict(self._routes)

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "num_replicas": len(self._replicas.get(name, [])),
                    "route_prefix": info.route_prefix,
                    "version": info.version,
                    "autoscaling": info.autoscaling_config is not None,
                }
                for name, info in self._deployments.items()
            }

    def report_failure(self, name: str, replica_id: str) -> None:
        """Router saw a dead replica: replace it (reference: replica recovery
        in DeploymentState reconciliation)."""
        with self._lock:
            replicas = self._replicas.get(name, [])
            before = len(replicas)
            replicas[:] = [r for r in replicas if r.replica_id != replica_id]
            if len(replicas) < before:
                self._bump(f"replicas::{name}")
                if name in self._deployments:
                    self._scale_to(name, before)

    # ------------------------------------------------------------ autoscaling
    def report_load(self, name: str, router_id: str, inflight: int) -> None:
        with self._lock:
            self._load.setdefault(name, {})[router_id] = (inflight, time.time())

    def _autoscale_loop(self):
        while not self._stop.wait(0.5):
            try:
                self._autoscale_once()
            except Exception:
                pass

    def _autoscale_once(self):
        now = time.time()
        with self._lock:
            for name, info in list(self._deployments.items()):
                cfg = info.autoscaling_config
                if cfg is None:
                    continue
                loads = self._load.get(name, {})
                total = sum(v for v, ts in loads.values() if now - ts < 5.0)
                cur = len(self._replicas.get(name, []))
                desired = max(
                    cfg.min_replicas,
                    min(
                        cfg.max_replicas,
                        -(-total // max(cfg.target_num_ongoing_requests_per_replica, 1e-9))
                        if total
                        else cfg.min_replicas,
                    ),
                )
                desired = int(desired)
                if desired > cur:
                    self._downscale_since[name] = None
                    self._scale_to(name, desired)
                elif desired < cur:
                    since = self._downscale_since.get(name)
                    if since is None:
                        self._downscale_since[name] = now
                    elif now - since >= cfg.downscale_delay_s:
                        self._scale_to(name, desired)
                        self._downscale_since[name] = None
                else:
                    self._downscale_since[name] = None

    def shutdown(self) -> None:
        with self._lock:
            for name in list(self._deployments):
                self._scale_to(name, 0)
            self._deployments.clear()
            self._replicas.clear()
            self._routes.clear()
            self._stop.set()
            self._change.notify_all()  # release parked long-polls
