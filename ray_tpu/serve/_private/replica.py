"""Replica: the actor wrapping one copy of a deployment's user callable.

Reference: `python/ray/serve/_private/replica.py:276` (`RayServeReplica`) —
resolves the user class/function, injects handle arguments, executes requests.
One request at a time (the actor's ordered queue); concurrency comes from
replica count, balanced by the router's power-of-two choice.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Tuple


class ServeReplica:
    def __init__(self, deployment_name: str, blob: bytes, init_args: Tuple,
                 init_kwargs: Dict[str, Any]):
        from ray_tpu._private import serialization

        self.deployment_name = deployment_name
        target = serialization.loads(blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            if init_args or init_kwargs:
                raise ValueError("function deployments take no init args")
            self._callable = target
        self._requests = 0
        self._started = time.time()

    def handle_request(self, method_name: str, args: Tuple, kwargs: Dict[str, Any]):
        self._requests += 1
        if method_name == "__call__":
            target = self._callable
            if not callable(target):
                raise AttributeError(
                    f"deployment {self.deployment_name} object is not callable"
                )
        else:
            target = getattr(self._callable, method_name)
        return target(*args, **kwargs)

    def stats(self) -> Dict[str, Any]:
        return {
            "deployment": self.deployment_name,
            "requests": self._requests,
            "uptime_s": time.time() - self._started,
        }

    def reconfigure(self, user_config: Any) -> None:
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
