"""Replica: the actor wrapping one copy of a deployment's user callable.

Reference: `python/ray/serve/_private/replica.py:276` (`RayServeReplica`) —
resolves the user class/function, injects handle arguments, executes requests.
By default one request at a time (the actor's ordered queue) with concurrency
from replica count, balanced by the router's power-of-two choice; the
deployment option `max_concurrent_queries > 1` runs calls on a thread pool
(async user methods then share the actor's one event loop — where
`@serve.batch` queues accumulate).
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, Tuple


class ServeReplica:
    def __init__(self, deployment_name: str, blob: bytes, init_args: Tuple,
                 init_kwargs: Dict[str, Any],
                 max_concurrent_queries: int = 1):
        import threading
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu._private import serialization

        # Graceful-drain bookkeeping: requests EXECUTING right now (calls
        # still parked in the actor's ordered queue are counted by the
        # scheduler's ActorRecord — the controller polls that side). The
        # draining flag is set out-of-band by the worker's reader thread
        # (serve_drain tag) or via prepare_drain(); stragglers routed by a
        # not-yet-pushed table still run — drain never drops admitted work.
        self._active = 0
        self._active_lock = threading.Lock()
        self._draining = False
        self.deployment_name = deployment_name
        target = serialization.loads(blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            if init_args or init_kwargs:
                raise ValueError("function deployments take no init args")
            self._callable = target
        # Lock-free under concurrent calls (threaded replicas).
        self._request_counter = itertools.count(1)
        self._requests = 0
        # Sync user code dispatched off the shared event loop runs HERE,
        # sized to the deployment's concurrency contract — the loop's default
        # executor caps at min(32, cpus+4) and is shared with sync-generator
        # chunk iteration, which would head-of-line block streams.
        self._sync_executor = ThreadPoolExecutor(
            max_workers=max(1, int(max_concurrent_queries)),
            thread_name_prefix=f"replica-sync-{deployment_name}",
        )
        self._started = time.time()

    def _count_request(self) -> None:
        self._requests = next(self._request_counter)

    # --------------------------------------------------------------- draining
    def _admit(self) -> None:
        with self._active_lock:
            self._active += 1

    def _release(self) -> None:
        with self._active_lock:
            self._active -= 1

    def _serve_begin_drain(self) -> None:
        """Out-of-band drain hook (worker reader thread, serve_drain tag)."""
        self._draining = True

    def _serve_inflight(self) -> int:
        return self._active

    def prepare_drain(self) -> int:
        """Actor-call form of the drain flag (threaded replicas; the wire
        form covers max_concurrency=1 replicas whose call queue is busy)."""
        self._draining = True
        return self._active

    async def _release_after(self, coro):
        # An async user method: the load unit must live until the coroutine
        # actually finishes, not until handle_request returns it.
        try:
            return await coro
        finally:
            self._release()

    def _resolve(self, method_name: str):
        if method_name == "__call__":
            target = self._callable
            if not callable(target):
                raise AttributeError(
                    f"deployment {self.deployment_name} object is not callable"
                )
            return target
        return getattr(self._callable, method_name)

    def handle_request(self, method_name: str, args: Tuple, kwargs: Dict[str, Any]):
        import inspect

        self._admit()
        try:
            out = self._handle_request_inner(method_name, args, kwargs)
        except BaseException:
            self._release()
            raise
        if inspect.iscoroutine(out):
            return self._release_after(out)
        self._release()
        return out

    def _handle_request_inner(self, method_name: str, args: Tuple,
                              kwargs: Dict[str, Any]):
        import inspect

        from ray_tpu.serve.multiplex import (
            MODEL_ID_KWARG,
            _reset_model_id,
            _run_with_model_id,
            _set_model_id,
        )

        self._count_request()
        model_id = kwargs.pop(MODEL_ID_KWARG, "")
        target = self._resolve(method_name)
        if not model_id:
            return target(*args, **kwargs)
        # Async: the ctxvar set must live inside the ONE task that drives the
        # user coroutine (task contexts persist across suspensions). Sync:
        # set/reset around the call in this thread.
        fn = target if inspect.isroutine(target) else getattr(
            target, "__call__", target
        )
        if inspect.iscoroutinefunction(fn):
            return _run_with_model_id(model_id, target(*args, **kwargs))
        token = _set_model_id(model_id)
        try:
            return target(*args, **kwargs)
        finally:
            _reset_model_id(token)

    async def handle_request_stream(self, method_name: str, args: Tuple,
                                    kwargs: Dict[str, Any]):
        self._admit()
        try:
            async for ev in self._handle_request_stream_inner(
                method_name, args, kwargs
            ):
                yield ev
        finally:
            self._release()

    async def _handle_request_stream_inner(self, method_name: str, args: Tuple,
                                           kwargs: Dict[str, Any]):
        """Streaming variant (called with num_returns="streaming"): a user
        method returning a generator streams each item as its own object; a
        plain return streams one ("single", value) event. First element of
        each event tells the consumer which case it is (reference: streaming
        deployment responses, `_private/replica.py` CallableWrapper gen path).

        An ASYNC generator: the worker drives it on the actor's shared event
        loop, so `async def` deployments (and their `@serve.batch` queues,
        which must see every concurrent request on ONE loop) work over the
        proxy's streaming path, not just the handle path. SYNC user code must
        never run on that shared loop — a blocking `def __call__` would
        serialize every concurrent request and starve pending batch drains —
        so sync targets (and sync-generator iteration) are pushed to the
        loop's thread pool."""
        import asyncio
        import functools
        import inspect

        from ray_tpu.serve.multiplex import (
            MODEL_ID_KWARG,
            _reset_model_id,
            _run_with_model_id,
            _set_model_id,
        )

        target = self._resolve(method_name)
        self._count_request()
        model_id = kwargs.pop(MODEL_ID_KWARG, "")
        # Class deployments resolve "__call__" to the INSTANCE: the async
        # check must look at its __call__ method, not the object.
        fn = target if inspect.isroutine(target) else getattr(
            target, "__call__", target
        )
        if inspect.iscoroutinefunction(fn) or inspect.isasyncgenfunction(fn):
            out = target(*args, **kwargs)
        else:
            def _call_sync():
                # Executor thread: set/reset the model-id ctxvar around the
                # user call (each pooled thread has its own context).
                if not model_id:
                    return target(*args, **kwargs)
                token = _set_model_id(model_id)
                try:
                    return target(*args, **kwargs)
                finally:
                    _reset_model_id(token)

            import contextvars

            loop = asyncio.get_running_loop()
            # copy_context: run_in_executor does NOT propagate contextvars,
            # and the request's ambient trace context (tracing.context_scope
            # set by the worker's coroutine driver) must reach the user call
            # so nested .remote()s join the request's trace.
            cctx = contextvars.copy_context()
            out = await loop.run_in_executor(
                self._sync_executor, functools.partial(cctx.run, _call_sync)
            )
        if inspect.iscoroutine(out):
            if model_id:
                # ensure_future: the user coroutine runs as ONE task whose
                # context (with the model id set) is stable across every
                # suspension — this async-generator frame itself resumes
                # under a FRESH context per __anext__ and cannot hold it.
                out = await asyncio.ensure_future(
                    _run_with_model_id(model_id, out)
                )
            else:
                out = await out
        if inspect.isgenerator(out):
            loop = asyncio.get_running_loop()
            sentinel = object()

            def _next():
                # Sync generator frames resume in THIS executor thread: set
                # the model id around each pull so the body sees it.
                if not model_id:
                    return next(out, sentinel)
                token = _set_model_id(model_id)
                try:
                    return next(out, sentinel)
                finally:
                    _reset_model_id(token)

            import contextvars

            gctx = contextvars.copy_context()
            while True:
                # Same contextvar propagation as the sync call above: the
                # generator body resumes on an executor thread and may make
                # nested traced calls.
                item = await loop.run_in_executor(
                    self._sync_executor, functools.partial(gctx.run, _next)
                )
                if item is sentinel:
                    break
                yield ("chunk", item)
        elif inspect.isasyncgen(out):
            if model_id:
                # Pump the user async-gen inside ONE task (stable context
                # carrying the model id); this frame resumes under a fresh
                # context per __anext__ and cannot hold the ctxvar itself.
                done = object()
                q: "asyncio.Queue" = asyncio.Queue(maxsize=2)

                async def _pump():
                    token = _set_model_id(model_id)
                    try:
                        async for item in out:
                            await q.put(("chunk", item))
                        await q.put((done, None))
                    except Exception as e:  # noqa: BLE001 — relayed below
                        await q.put(("err", e))
                    finally:
                        _reset_model_id(token)

                task = asyncio.ensure_future(_pump())
                try:
                    while True:
                        kind, item = await q.get()
                        if kind is done:
                            break
                        if kind == "err":
                            raise item
                        yield ("chunk", item)
                finally:
                    task.cancel()
            else:
                async for item in out:
                    yield ("chunk", item)
        else:
            yield ("single", out)

    def handle_asgi(self, scope: Dict[str, Any], body: bytes):
        self._admit()
        try:
            yield from self._handle_asgi_inner(scope, body)
        finally:
            self._release()

    def _handle_asgi_inner(self, scope: Dict[str, Any], body: bytes):
        """Run one HTTP request through the deployment's ASGI app, yielding
        ASGI messages ({"type": "http.response.start"/"http.response.body"})
        as the app sends them — consumed by the proxy over a streaming actor
        call, so chunked/SSE responses stream end-to-end (reference:
        `serve.ingress` ASGI mounting, `python/ray/serve/api.py:160` +
        `http_util.py ASGIReceiveProxy`)."""
        import asyncio
        import queue as q
        import threading

        app = getattr(self._callable, "__serve_asgi_app__", None)
        if app is None:
            raise AttributeError(
                f"deployment {self.deployment_name} is not an ASGI ingress "
                "(decorate the class with @serve.ingress(app))"
            )
        self._count_request()
        # Rebuild bytes-typed scope fields lost to the wire format.
        scope = dict(scope)
        scope["query_string"] = scope.get("query_string", b"") or b""
        scope["headers"] = [
            (k.encode() if isinstance(k, str) else k,
             v.encode() if isinstance(v, str) else v)
            for k, v in scope.get("headers", [])
        ]
        events: "q.Queue" = q.Queue()
        _END = object()
        got_body = {"v": False}
        response_done: Dict[str, Any] = {"event": None}

        async def receive():
            # First call: the (complete) request body. Later calls park until
            # the response finishes, then deliver http.disconnect — this
            # serves both disconnect-watch patterns: a side task (Starlette's
            # listen_for_disconnect) parks harmlessly, and a main-coroutine
            # `send everything, then await receive()` unblocks at the end.
            # A hot-returning receive would spin and starve the response task.
            if not got_body["v"]:
                got_body["v"] = True
                return {"type": "http.request", "body": body, "more_body": False}
            import asyncio as aio

            if response_done["event"] is None:
                response_done["event"] = aio.Event()
            await response_done["event"].wait()
            return {"type": "http.disconnect"}

        async def send(message):
            events.put(message)
            if message.get("type") == "http.response.body" and not message.get(
                "more_body", False
            ):
                ev = response_done["event"]
                if ev is None:
                    import asyncio as aio

                    response_done["event"] = ev = aio.Event()
                ev.set()

        # Multiplexed routing over ASGI: the header sets the request context
        # (the app coroutine runs as one task in this private loop, so the
        # ctxvar set in the runner thread is captured for its whole life).
        from ray_tpu.serve.multiplex import MODEL_ID_HEADER, _set_model_id

        model_id = ""
        for k, v in scope["headers"]:
            if k.decode().lower() == MODEL_ID_HEADER:
                model_id = v.decode()
                break

        # The app coroutine runs on its own thread: hand it the request's
        # ambient trace context so nested traced calls join the trace.
        from ray_tpu.util import tracing

        trace_ctx = (
            tracing.current_trace_context() if tracing.is_enabled() else None
        )

        def run():
            if model_id:
                _set_model_id(model_id)
            loop = asyncio.new_event_loop()
            try:
                with tracing.context_scope(trace_ctx):
                    loop.run_until_complete(app(scope, receive, send))
            except Exception as e:  # noqa: BLE001 — surfaced as a 500 event
                events.put({"type": "asgi.error", "error": repr(e)})
            finally:
                loop.close()
                events.put(_END)

        threading.Thread(target=run, daemon=True, name="asgi-call").start()
        while True:
            ev = events.get()
            if ev is _END:
                return
            yield ev

    def stats(self) -> Dict[str, Any]:
        return {
            "deployment": self.deployment_name,
            "requests": self._requests,
            "inflight": self._active,
            "draining": self._draining,
            "uptime_s": time.time() - self._started,
        }

    def reconfigure(self, user_config: Any) -> None:
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
