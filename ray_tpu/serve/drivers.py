"""DAGDriver: serve a ray_tpu.dag graph (or several, keyed by route) over HTTP.

Reference: `python/ray/serve/drivers.py:29` (`DAGDriver`) — the ingress
deployment for model-composition graphs: each request's payload becomes the
graph's `InputNode`, the DAG executes across tasks/actors/deployment handles,
and the root's result is the response.

Usage::

    with InputNode() as inp:            # or plain InputNode()
        a = preprocess.bind(inp)
        out = model.bind(a)
    serve.run(serve.deployment(DAGDriver).bind(out))
    # or multiple routes:
    serve.run(serve.deployment(DAGDriver).bind({"/a": dag_a, "/b": dag_b}))
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Union

import ray_tpu


def json_request(request) -> Any:
    """Default http_adapter: JSON body if present, else the query params."""
    if getattr(request, "body", b""):
        return json.loads(request.body)
    qp = getattr(request, "query_params", None)
    return dict(qp) if qp else None


class DAGDriver:
    def __init__(
        self,
        dags: Union[Any, Dict[str, Any]],
        *,
        http_adapter: Optional[Callable[[Any], Any]] = None,
    ):
        self._routes: Optional[Dict[str, Any]] = (
            dict(dags) if isinstance(dags, dict) else None
        )
        self._dag = None if self._routes is not None else dags
        self._adapter = http_adapter or json_request

    def _dag_for(self, path: str):
        if self._routes is None:
            return self._dag
        dag = self._routes.get(path) or self._routes.get(path.rstrip("/") or "/")
        if dag is None:
            raise KeyError(f"no DAG bound at route {path!r}")
        return dag

    def _execute(self, dag, payload):
        out = dag.execute(payload)
        # The root returns an ObjectRef (task/actor-method node) or a plain
        # value (InputNode root); resolve refs before responding.
        if isinstance(out, ray_tpu.ObjectRef):
            return ray_tpu.get(out)
        return out

    def __call__(self, request):
        """HTTP entry: adapt the request, run the matching DAG."""
        return self._execute(self._dag_for(getattr(request, "path", "/")),
                             self._adapter(request))

    def predict(self, payload):
        """Python-handle entry: run the (single) DAG on the given payload."""
        return self._execute(self._dag_for("/"), payload)

    def predict_with_route(self, path: str, payload):
        return self._execute(self._dag_for(path), payload)
