"""DeploymentHandle + Router: the client-side request path.

Reference: `python/ray/serve/handle.py` + `_private/router.py:263` — a handle
routes each call to a replica via power-of-two-choices over the router's
outstanding-request counts. Replica membership is PUSHED: a background
listener parks in the controller's `listen_for_change` long poll (the client
half of the reference's LongPollHost, `long_poll.py:185`) and swaps the local
table the moment the replica set changes — no TTL staleness window. Dead
replicas are reported to the controller (which replaces them) and the call
retries on another replica.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_LOAD_REPORT_INTERVAL_S = 0.5
# Model-affinity escape hysteresis: the sticky replica keeps a model's
# traffic until its in-flight load exceeds the power-of-two alternative's by
# more than this (or hits max_concurrent_queries) — switching replicas pays a
# model reload, so a 1-request imbalance must not thrash the affinity map.
_AFFINITY_ESCAPE_THRESHOLD = 2

# Every live Router in this process; serve.shutdown() closes them so their
# long-poll listeners release controller call slots.
import weakref

_all_routers: "weakref.WeakSet" = weakref.WeakSet()

def _metrics():
    """Router metric set, or None when enable_metrics is off. The knob is
    re-read per call (an init/shutdown cycle may flip it); the metric
    objects themselves are cached inside telemetry.router_metrics()."""
    from ray_tpu._private import telemetry

    return telemetry.router_metrics() if telemetry.metrics_enabled() else None


def close_all_routers() -> None:
    for r in list(_all_routers):
        r.close()


def _router_listen_loop(router_ref, deployment_name: str, controller):
    """Long-poll client parked at the controller. Holds only a WEAKREF to
    its router: when the last handle drops, the router is GC'd, its
    __del__ cancels the parked listener (cancel_listener) and this thread
    exits — controller call slots don't leak across app redeploys
    (previously the bound-method thread target kept every router alive
    forever)."""
    import ray_tpu

    key = f"replicas::{deployment_name}"
    version = -1
    failures = 0
    while True:
        r = router_ref()
        if r is None or r._closed:
            return
        router_id = r._router_id
        del r  # never hold the router across the blocking poll
        try:
            updates = ray_tpu.get(
                controller.listen_for_change.remote(
                    {key: version}, router_id
                ),
                timeout=60,
            )
            failures = 0
        except Exception:
            failures += 1
            if failures >= 6:
                # Controller gone (serve.shutdown without closing handles):
                # stop spinning; route() falls back to direct fetches.
                return
            time.sleep(0.5)
            continue
        r = router_ref()
        if r is None or r._closed:
            return
        if key in updates:
            version, replicas = updates[key]
            with r._lock:
                r._version = version
                r._replicas = replicas
            r._have_table.set()
        del r


class Router:
    def __init__(self, deployment_name: str, controller):
        self._name = deployment_name
        self._controller = controller
        self._router_id = uuid.uuid4().hex[:8]
        self._lock = threading.Lock()
        self._replicas: List = []  # ReplicaInfo
        self._version = -1  # -1 = never synced; first listen returns current
        self._have_table = threading.Event()
        self._inflight: Dict[str, List[Any]] = {}  # replica_id -> pending refs
        # Streaming calls have no single ref to sweep: consumers decrement
        # via stream_done() when the stream ends/closes, so load reports (and
        # with them autoscaling) see HTTP/streaming traffic too.
        self._inflight_streams: Dict[str, int] = {}
        # stream_done must be GC-safe (DeploymentResponseGenerator.__del__):
        # lock-free queue drained under the lock by _sweep.
        import collections

        self._stream_done_q: "collections.deque" = collections.deque()
        # Multiplexed model affinity: model_id -> replica_id that last served
        # it (its LRU holds the loaded weights; route traffic back there).
        # Bounded LRU: per-tenant one-shot ids must not grow the router
        # without limit.
        import collections as _c

        self._model_affinity: "_c.OrderedDict[str, str]" = _c.OrderedDict()
        self._model_affinity_cap = 4096
        self._last_load_report = 0.0
        # Route-wait samples (ts, seconds) for the windowed p95 reported to
        # the controller — the SLO-aware autoscaling signal. Own lock: the
        # append happens after route() releases self._lock, while the p95
        # scan iterates from under it — iterating a deque another thread is
        # appending to raises RuntimeError.
        import collections as _c2

        self._wait_samples: "_c2.deque" = _c2.deque(maxlen=2048)
        self._samples_lock = threading.Lock()
        self._closed = False
        _all_routers.add(self)
        threading.Thread(
            target=_router_listen_loop,
            args=(weakref.ref(self), deployment_name, controller),
            daemon=True, name=f"serve-listen-{deployment_name}",
        ).start()

    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            # Unpark this router's listener so its controller call slot
            # frees now, not at the next server-side timeout.
            self._controller.cancel_listener.remote(self._router_id)
        except Exception:
            pass

    def __del__(self):
        # GC-driven close (the weakref listen loop makes routers
        # collectable): a leaked slot per redeploy otherwise.
        try:
            self.close()
        except Exception:
            pass

    def _ensure_table(self, force: bool = False):
        """Ensure a table exists. Steady-state updates arrive via push; this
        only blocks on the very first request (or re-pulls after a reported
        failure, where waiting for the push would race the retry). MUST be
        called without self._lock held: the listener needs that lock to apply
        the push this may be waiting for."""
        import ray_tpu

        if self._replicas and not force:
            return
        if not force and self._have_table.wait(timeout=5.0) and self._replicas:
            return
        replicas = ray_tpu.get(self._controller.get_replicas.remote(self._name))
        with self._lock:
            if force or not self._replicas:
                self._replicas = replicas

    def _sweep(self):
        """Drop completed refs from the inflight books (lazy decrement) and
        apply queued stream completions."""
        import ray_tpu

        while True:
            try:
                rid = self._stream_done_q.popleft()
            except IndexError:
                break
            n = self._inflight_streams.get(rid, 0)
            if n <= 1:
                self._inflight_streams.pop(rid, None)
            else:
                self._inflight_streams[rid] = n - 1
        for rid, refs in list(self._inflight.items()):
            if not refs:
                continue
            ready, not_ready = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0
            )
            self._inflight[rid] = not_ready

    def _load_of(self, replica_id: str) -> int:
        return len(self._inflight.get(replica_id, [])) + self._inflight_streams.get(
            replica_id, 0
        )

    def _route_wait_p95(self) -> "Optional[tuple]":
        """(p95_seconds, exemplar_trace_id) of route-wait samples inside the
        SLO window (PR 2's histogram signal, windowed locally so the
        controller sees CURRENT latency, not all-time). The exemplar is the
        trace id of the p95 sample itself (None when that request was
        untraced). None with no fresh samples."""
        from ray_tpu._private.config import get_config

        cutoff = time.time() - float(get_config().serve_slo_window_s)
        with self._samples_lock:
            snapshot = list(self._wait_samples)
        recent = sorted(
            ((s[1], s[2] if len(s) > 2 else None)
             for s in snapshot if s[0] >= cutoff),
            key=lambda x: x[0],
        )
        if not recent:
            return None
        return recent[min(len(recent) - 1, int(0.95 * len(recent)))]

    def _report_load(self):
        now = time.time()
        if now - self._last_load_report < _LOAD_REPORT_INTERVAL_S:
            return
        self._last_load_report = now
        total = sum(len(v) for v in self._inflight.values()) + sum(
            self._inflight_streams.values()
        )
        sample = self._route_wait_p95()
        p95 = sample[0] if sample else None
        m = _metrics()
        if m is not None:
            # Replica saturation: this router's in-flight load over the
            # replica set's total concurrency capacity. Reported at load-
            # report cadence, not per request.
            capacity = sum(
                max(1, getattr(r, "max_concurrent_queries", 1))
                for r in self._replicas
            )
            tags = {"deployment": self._name}
            m["inflight"].set(total, tags)
            if capacity:
                m["saturation"].set(total / capacity, tags)
            if p95 is not None:
                # The p95 sample's own trace rides as the gauge exemplar, so
                # a firing route-wait SLO alert links to a concrete slow
                # trace (state.get_trace / /api/traces).
                m["slo_p95"].set(p95, tags, exemplar=sample[1])
        try:
            self._controller.report_load.remote(
                self._name, self._router_id, total, p95
            )
        except Exception:
            pass

    def stream_done(self, replica_id: str) -> None:
        """A streaming call finished or was dropped: release its load unit.
        Lock-free (callable from __del__); applied at the next _sweep."""
        self._stream_done_q.append(replica_id)

    def _maybe_shed_overload(self):
        """Per-replica inflight cap (admission control's router half): when
        EVERY replica is loaded past max_concurrent_queries * the cap
        factor, queueing deeper only grows tail latency — shed instead.
        Called under self._lock. Off by default (factor 0): the proxy's
        per-app cap is the primary gate; this one bounds the router's own
        books under direct-handle flood."""
        from ray_tpu._private.config import get_config

        cfg = get_config()
        factor = float(cfg.serve_replica_inflight_cap_factor)
        if factor <= 0:
            return
        from ray_tpu.serve._private.common import RequestShedded

        for r in self._replicas:
            cap = max(1, getattr(r, "max_concurrent_queries", 1)) * factor
            if self._load_of(r.replica_id) < cap:
                return
        from ray_tpu._private import telemetry

        if telemetry.metrics_enabled():
            telemetry.serve_ingress_metrics()["shed"].inc(
                1, {"app": self._name, "reason": "replica_inflight"}
            )
        raise RequestShedded(
            f"all replicas of '{self._name}' at "
            f"max_concurrent_queries x {factor:g}",
            reason="replica_inflight",
            retry_after_s=cfg.serve_retry_after_s,
        )

    def route(self, method_name: str, args, kwargs, force_refresh: bool = False,
              stream: bool = False, raw_method: bool = False,
              trace_ctx: Optional[Dict[str, str]] = None):
        """Pick a replica (power of two choices) and submit.

        Returns ``(ref, replica_id)`` so the response can report the replica
        on actor-death and resubmit (dead-replica retry lives in
        DeploymentResponse.result()). With ``stream=True`` the first element
        is an ObjectRefGenerator from a streaming call to
        `handle_request_stream` (or to `method_name` itself when
        ``raw_method`` — the proxy's ASGI path). ``trace_ctx`` is the
        request's trace context handed down from the HTTP proxy (the root
        span owner): route() opens a "router" child span covering the
        route wait and scopes the replica submit under it, so the actor
        call's submit/execute spans join the SAME trace."""
        from ray_tpu.util import tracing

        if trace_ctx is None:
            # Direct handle calls inside a traced caller (a replica fanning
            # out, a traced driver) still join the ambient trace.
            trace_ctx = tracing.current_trace_context()
        rspan = None
        if trace_ctx is not None and tracing.is_enabled():
            # Detached: route() may run on a shared event-loop thread; the
            # span must not leak into unrelated requests' thread-local state.
            rspan = tracing.start_span(
                f"route::{self._name}", "router", trace_context=trace_ctx,
                detached=True,
            )
        try:
            return self._route_inner(
                method_name, args, kwargs, force_refresh, stream, raw_method,
                trace_ctx, rspan,
            )
        except BaseException:
            # A shed/no-replica/submit failure must still close (and flush)
            # the router span: these are exactly the requests a trace is
            # supposed to explain.
            tracing.end_span(rspan, "ERROR")
            raise

    def _route_inner(self, method_name: str, args, kwargs,
                     force_refresh: bool, stream: bool, raw_method: bool,
                     trace_ctx, rspan):
        from ray_tpu.actor import ActorHandle

        from ray_tpu.serve.multiplex import MODEL_ID_KWARG
        from ray_tpu.util import tracing

        t_route = time.perf_counter()
        scope_ctx = tracing.context_of(rspan) or trace_ctx
        model_id = ""
        if kwargs and MODEL_ID_KWARG in kwargs:
            # raw_method calls go straight to the named replica method (ASGI
            # path) — the reserved kwarg is routing metadata only and must
            # not reach its signature; the normal path's replica pops it.
            model_id = (
                kwargs.pop(MODEL_ID_KWARG) if raw_method
                else kwargs[MODEL_ID_KWARG]
            )
        self._ensure_table(force=force_refresh)  # outside the lock (push needs it)
        with self._lock:
            if not self._replicas:
                raise RuntimeError(f"no replicas for deployment '{self._name}'")
            self._sweep()
            self._maybe_shed_overload()
            chosen = None
            if model_id:
                # Sticky model routing: the replica that served this model
                # already paid its load cost (reference: multiplexed-aware
                # scheduling). Falls through when it died or was scaled away.
                rid = self._model_affinity.get(model_id)
                if rid is not None:
                    chosen = next(
                        (r for r in self._replicas if r.replica_id == rid), None
                    )
                if chosen is not None and len(self._replicas) > 1:
                    # Load-based escape: affinity must not pin a hot model's
                    # traffic to one replica while others idle. When the
                    # sticky replica is at its concurrency cap, or ahead of a
                    # power-of-two alternative by more than the hysteresis
                    # threshold (re-loading weights costs something), fall
                    # back to the alternative and re-point the affinity map.
                    aff_load = self._load_of(chosen.replica_id)
                    others = [
                        r for r in self._replicas
                        if r.replica_id != chosen.replica_id
                    ]
                    alt = min(
                        random.sample(others, min(2, len(others))),
                        key=lambda r: self._load_of(r.replica_id),
                    )
                    alt_load = self._load_of(alt.replica_id)
                    if aff_load >= chosen.max_concurrent_queries and (
                        alt_load < alt.max_concurrent_queries
                        or alt_load < aff_load
                    ):
                        chosen = alt
                    elif aff_load > alt_load + _AFFINITY_ESCAPE_THRESHOLD:
                        chosen = alt
            if chosen is None:
                if len(self._replicas) == 1:
                    chosen = self._replicas[0]
                else:
                    a, b = random.sample(self._replicas, 2)
                    chosen = (
                        a
                        if self._load_of(a.replica_id) <= self._load_of(b.replica_id)
                        else b
                    )
            if model_id:
                self._model_affinity[model_id] = chosen.replica_id
                self._model_affinity.move_to_end(model_id)
                while len(self._model_affinity) > self._model_affinity_cap:
                    self._model_affinity.popitem(last=False)
            handle = ActorHandle(chosen.actor_id, "ServeReplica")
            # The scope makes the router span (or the handed-down request
            # context) the ambient parent for the actor-call submit span, so
            # proxy -> router -> replica-execute form ONE trace.
            with tracing.context_scope(scope_ctx):
                if stream:
                    if raw_method:
                        method = getattr(handle, method_name)
                        ref = method.options(num_returns="streaming").remote(*args, **kwargs)
                    else:
                        ref = handle.handle_request_stream.options(
                            num_returns="streaming"
                        ).remote(method_name, tuple(args), kwargs)
                    self._inflight_streams[chosen.replica_id] = (
                        self._inflight_streams.get(chosen.replica_id, 0) + 1
                    )
                else:
                    ref = handle.handle_request.remote(method_name, tuple(args), kwargs)
                    self._inflight.setdefault(chosen.replica_id, []).append(ref)
            self._report_load()
        wait = time.perf_counter() - t_route
        if rspan is not None:
            rspan["attributes"]["replica_id"] = chosen.replica_id
            tracing.end_span(rspan)
        trace_id = trace_ctx.get("trace_id") if trace_ctx else None
        # Sampled regardless of enable_metrics: the SLO autoscaler needs the
        # p95 signal even on a metrics-off runtime (append is O(1), bounded).
        with self._samples_lock:
            self._wait_samples.append((time.time(), wait, trace_id))
        m = _metrics()
        if m is not None:
            tags = {"deployment": self._name}
            m["requests"].inc(1, tags)
            # Route wait: table fetch + lock + replica pick + submit — the
            # router-side queueing a request pays before reaching a replica.
            # The trace id rides as an EXEMPLAR: a route-wait observation in
            # the series store links back to the concrete trace that paid it.
            m["route_wait"].observe(wait, tags, exemplar=trace_id)
        return ref, chosen.replica_id

    def report_failure(self, replica_id: str):
        import ray_tpu

        try:
            ray_tpu.get(
                self._controller.report_failure.remote(self._name, replica_id)
            )
        except Exception:
            pass
        with self._lock:
            self._replicas = [r for r in self._replicas if r.replica_id != replica_id]
            for mid in [
                m for m, r in self._model_affinity.items() if r == replica_id
            ]:
                del self._model_affinity[mid]


class DeploymentResponse:
    """Lazy response: `.result()` blocks, `ray_tpu.get(resp.ref)` also works
    (reference: `serve/handle.py` DeploymentResponse).

    On actor-death at fetch time the dead replica is reported to the
    controller (which replaces it) and the request is resubmitted to another
    replica under the unified retry policy (`_private/retry.py`):
    `Config.serve_resubmit_attempts` bounded attempts with seeded backoff,
    all inside the caller's timeout budget. Each failover increments
    `ray_tpu_serve_resubmit_total{deployment}`."""

    def __init__(
        self,
        ref,
        router: Router,
        replica_id: Optional[str] = None,
        request: Optional[tuple] = None,
    ):
        self.ref = ref
        self._router = router
        self._replica_id = replica_id
        self._request = request  # (method_name, args, kwargs)

    def result(self, timeout: Optional[float] = None):
        import ray_tpu
        from ray_tpu._private import retry
        from ray_tpu._private.config import get_config
        from ray_tpu.exceptions import RayActorError, WorkerCrashedError

        cfg = get_config()
        deadline = None if timeout is None else time.monotonic() + timeout
        attempts_left = max(0, int(cfg.serve_resubmit_attempts))
        # Deterministic backoff between failovers (seeded from the request's
        # first replica via retry.seed_from — stable across processes):
        # replacing replicas need a beat to come up.
        delays = retry.backoff_delays(
            retry.RetryPolicy.from_config(cfg, max_attempts=attempts_left + 1),
            seed=retry.seed_from(self._replica_id or ""),
        )
        while True:
            remaining = (
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            try:
                return ray_tpu.get(
                    self.ref, timeout=remaining if timeout is not None else None
                )
            except (RayActorError, WorkerCrashedError):
                if self._request is None or self._replica_id is None:
                    raise
                if attempts_left <= 0:
                    raise
                # The retry's controller round-trips are not individually
                # bounded; at minimum don't start them with the caller's
                # budget already spent.
                if deadline is not None and time.monotonic() >= deadline:
                    from ray_tpu.exceptions import GetTimeoutError

                    raise GetTimeoutError(
                        f"request to dead replica {self._replica_id} had no "
                        f"budget left to retry within timeout={timeout}s"
                    )
                attempts_left -= 1
                m = _metrics()
                if m is not None:
                    m["resubmits"].inc(
                        1, {"deployment": self._router._name}
                    )
                # Report the dead replica FIRST so the controller starts the
                # replacement during the backoff sleep, not after it.
                self._router.report_failure(self._replica_id)
                delay = next(delays, 0.0)
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline - time.monotonic()))
                if delay > 0:
                    time.sleep(delay)
                method, args, kwargs = self._request
                self.ref, self._replica_id = self._router.route(
                    method, args, kwargs, force_refresh=True
                )


class _ReplicaStream:
    """One streaming call to a replica: pulls values off the core
    ObjectRefGenerator, resubmits on another replica under the unified retry
    policy (`serve_resubmit_attempts` bounded attempts with seeded backoff,
    counted in `ray_tpu_serve_resubmit_total`) if the chosen one died before
    producing anything, and releases the router's stream load unit when the
    stream ends, errors, or is closed. Mid-stream death (items already
    delivered) is never transparently retried."""

    def __init__(self, router: Router, method_name: str, args, kwargs,
                 raw_method: bool = False, trace_ctx=None):
        from ray_tpu._private import retry
        from ray_tpu._private.config import get_config

        self._router = router
        self._call = (method_name, args, kwargs, raw_method)
        self._trace_ctx = trace_ctx  # request envelope context (HTTP proxy)
        self._gen, self._rid = router.route(
            method_name, args, kwargs, stream=True, raw_method=raw_method,
            trace_ctx=trace_ctx,
        )
        self._got_first = False
        cfg = get_config()
        self._resubmits_left = max(0, int(cfg.serve_resubmit_attempts))
        self._delays = retry.backoff_delays(
            retry.RetryPolicy.from_config(
                cfg, max_attempts=self._resubmits_left + 1
            ),
            seed=retry.seed_from(self._rid or ""),
        )
        self._done = False

    @property
    def replica_id(self) -> str:
        return self._rid

    def next_or_none(self):
        """The next streamed value, or None at end-of-stream."""
        import ray_tpu
        from ray_tpu.exceptions import RayActorError, WorkerCrashedError

        while True:
            try:
                ref = next(self._gen)
                value = ray_tpu.get(ref)
                self._got_first = True
                return value
            except StopIteration:
                self._finish()
                return None
            except (RayActorError, WorkerCrashedError):
                if self._got_first or self._resubmits_left <= 0:
                    # Mid-stream death is not transparently retryable (items
                    # already delivered); surface it.
                    self._finish()
                    raise
                self._resubmits_left -= 1
                m = _metrics()
                if m is not None:
                    m["resubmits"].inc(1, {"deployment": self._router._name})
                # Report first (controller starts the replacement during the
                # backoff sleep), then back off, then re-route.
                self._router.report_failure(self._rid)
                self._router.stream_done(self._rid)
                delay = next(self._delays, 0.0)
                if delay > 0:
                    time.sleep(delay)
                method, args, kwargs, raw = self._call
                self._gen, self._rid = self._router.route(
                    method, args, kwargs, force_refresh=True,
                    stream=True, raw_method=raw, trace_ctx=self._trace_ctx,
                )
            except BaseException:
                # User exception from the deployment (or any other failure):
                # the stream is over — release the load unit before raising.
                self._finish()
                raise

    def close(self):
        if not self._done:
            try:
                self._gen.close()
            finally:
                self._finish()

    def _finish(self):
        if not self._done:
            self._done = True
            self._router.stream_done(self._rid)

    def __del__(self):
        # Abandoned stream: releasing the load unit is GC-safe (lock-free
        # queue); the core generator's own __del__ releases its items.
        try:
            self._finish()
        except Exception:
            pass


class DeploymentResponseGenerator:
    """Streaming response: iterating yields the values a generator deployment
    method produces, as they are produced (reference: `serve/handle.py`
    `DeploymentResponseGenerator`, `handle.options(stream=True)`)."""

    def __init__(self, stream: _ReplicaStream):
        self._stream = stream

    def __iter__(self):
        return self

    def __next__(self):
        event = self._stream.next_or_none()
        if event is None:
            raise StopIteration
        _kind, value = event
        return value

    def close(self):
        self._stream.close()


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 method_name: str = "__call__", stream: bool = False,
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method_name
        self._stream = stream
        self._multiplexed_model_id = multiplexed_model_id
        self._router: Optional[Router] = None

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name,
            self._controller,
            method_name if method_name is not None else self._method,
            stream if stream is not None else self._stream,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self._multiplexed_model_id,
        )
        # Derived handles SHARE the parent's router: one replica table, one
        # load book, one model-affinity map — and no router (+ its listener
        # thread) per options()/bound-method call.
        h._router = self._ensure_router()
        return h

    def _ensure_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name, self._controller)
        return self._router

    def remote(self, *args, **kwargs):
        if self._multiplexed_model_id:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._multiplexed_model_id}
        router = self._ensure_router()
        if self._stream:
            return DeploymentResponseGenerator(
                _ReplicaStream(router, self._method, args, kwargs)
            )
        ref, replica_id = router.route(self._method, args, kwargs)
        return DeploymentResponse(
            ref, router, replica_id, (self._method, args, kwargs)
        )

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.deployment_name, self._controller, self._method, self._stream,
             self._multiplexed_model_id),
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)


class _BoundMethod:
    def __init__(self, handle: DeploymentHandle, method_name: str):
        self._h = handle
        self._m = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._h.options(method_name=self._m).remote(*args, **kwargs)
