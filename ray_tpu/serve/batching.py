"""Dynamic request batching: `@serve.batch`.

Reference: `python/ray/serve/batching.py` (`@serve.batch` — concurrent
single-item calls accumulate into one vectorized call of up to
`max_batch_size` items, flushed when full or after `batch_wait_timeout_s`).

TPU-first rationale: a replica serving single requests wastes the MXU —
batching N requests into one forward multiplies arithmetic intensity at the
cost of `batch_wait_timeout_s` latency. Pair with the deployment option
`max_concurrent_queries > 1` (threaded replica calls share one asyncio loop,
where the queue lives); with one-at-a-time replicas there is never a second
in-flight request to batch with.
"""

from __future__ import annotations

import inspect
from typing import Any, List, Optional, Tuple


class _BatchQueue:
    """Accumulates (item, future, enqueue_ts) triples on the running event
    loop; one drain task flushes full or timed-out batches through the
    wrapped function.

    Shedding: with `max_queue_len` set, a submit finding the queue at
    capacity is rejected IMMEDIATELY with RequestShedded (fast 503 at the
    front door) instead of deepening the backlog; with `shed_timeout_s`
    set, members that waited past it are shed individually at flush time —
    one slow batch must not time out every queued member behind it. A
    member is settled exactly once (executed OR shed): the shed scan runs
    after the batch is popped, and both paths guard on fut.done()."""

    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float,
                 max_queue_len: int = 0,
                 shed_timeout_s: Optional[float] = None):
        self._fn = fn
        self.max_batch_size = int(max_batch_size)
        self.batch_wait_timeout_s = float(batch_wait_timeout_s)
        self.max_queue_len = int(max_queue_len)
        self.shed_timeout_s = shed_timeout_s
        self._items: List[Tuple[Any, Any, float]] = []
        self._loop: Optional[Any] = None
        self._full: Optional[Any] = None
        self._drainer: Optional[Any] = None
        # Observability: sizes of executed batches (surfaced in tests and
        # debugging; the reference exposes similar counters via metrics).
        self.batch_sizes: List[int] = []
        # Members shed (queue cap + stale-wait), surfaced in tests/stats.
        self.shed_count = 0

    def _bind_loop(self, loop) -> None:
        """The Event (and the drainer task) belong to ONE event loop. A queue
        reused after its loop closed (asyncio.run called twice) rebinds
        cleanly when idle; mixing live loops with pending items cannot work —
        futures resolve only on their creating loop — so fail loudly instead
        of hanging the second caller forever."""
        import asyncio

        if self._loop is loop:
            return
        if self._items:
            if self._loop is not None and self._loop.is_closed():
                # The first loop died with items still queued (e.g. a caller
                # cancelled out of submit and asyncio.run tore down): their
                # waiters are gone with that loop — drop the orphans instead
                # of bricking the queue forever.
                self._items.clear()
            else:
                raise RuntimeError(
                    "@serve.batch queue used from a second event loop while "
                    "items are pending on the first"
                )
        self._loop = loop
        self._full = asyncio.Event()
        self._drainer = None

    async def submit(self, self_obj, item):
        import asyncio
        import time

        from ray_tpu.serve._private.common import RequestShedded

        loop = asyncio.get_running_loop()
        self._bind_loop(loop)
        if self.max_queue_len and len(self._items) >= self.max_queue_len:
            from ray_tpu._private.config import get_config

            # Admission control at the queue door: shedding here is what
            # keeps a saturated batch deployment answering in O(1) instead
            # of timing out ALL queued members together.
            self.shed_count += 1
            raise RequestShedded(
                f"@serve.batch queue at capacity ({self.max_queue_len})",
                reason="batch_queue",
                retry_after_s=get_config().serve_retry_after_s,
            )
        fut = loop.create_future()
        self._items.append((item, fut, time.monotonic()))
        if len(self._items) >= self.max_batch_size:
            self._full.set()
        if self._drainer is None or self._drainer.done():
            self._drainer = loop.create_task(self._drain(self_obj))
        return await fut

    def _shed_stale(self, batch):
        """Split a popped batch into (live, shed) by shed_timeout_s. Runs
        AFTER the pop, so the flush timer and the shed race settle each
        future exactly once (both sides guard on fut.done())."""
        import time

        from ray_tpu.serve._private.common import RequestShedded

        if self.shed_timeout_s is None:
            return batch
        from ray_tpu._private.config import get_config

        retry_after = get_config().serve_retry_after_s
        now = time.monotonic()
        live = []
        for item, fut, ts in batch:
            if now - ts > self.shed_timeout_s:
                self.shed_count += 1
                if not fut.done():
                    fut.set_exception(RequestShedded(
                        f"@serve.batch member waited "
                        f"{now - ts:.3f}s > shed_timeout_s="
                        f"{self.shed_timeout_s}", reason="batch_queue",
                        retry_after_s=retry_after,
                    ))
            else:
                live.append((item, fut, ts))
        return live

    async def _drain(self, self_obj) -> None:
        import asyncio

        while self._items:
            if len(self._items) < self.max_batch_size:
                try:
                    await asyncio.wait_for(
                        self._full.wait(), self.batch_wait_timeout_s
                    )
                except asyncio.TimeoutError:
                    pass
            self._full.clear()
            batch = self._items[: self.max_batch_size]
            del self._items[: len(batch)]
            batch = self._shed_stale(batch)
            if not batch:
                continue
            items = [it for it, _, _ in batch]
            try:
                if self_obj is not None:
                    results = await self._fn(self_obj, items)
                else:
                    results = await self._fn(items)
                if not isinstance(results, (list, tuple)) or len(results) != len(
                    items
                ):
                    raise TypeError(
                        "@serve.batch function must return a list with one "
                        f"result per input ({len(items)} expected, got "
                        f"{type(results).__name__}"
                        + (
                            f" of length {len(results)}"
                            if isinstance(results, (list, tuple))
                            else ""
                        )
                        + ")"
                    )
            except Exception as e:  # noqa: BLE001 — every waiter sees the error
                for _, fut, _ in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            self.batch_sizes.append(len(items))
            for (_, fut, _), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)


class _BatchWrapper:
    """Descriptor form of @serve.batch: binding to an instance lazily creates
    that instance's queue (replicas must not share batches across instances)."""

    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float,
                 max_queue_len: int = 0,
                 shed_timeout_s: Optional[float] = None):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._max_queue = max_queue_len
        self._shed_timeout = shed_timeout_s
        self._queue_attr = f"__serve_batch_queue_{fn.__name__}__"
        self._free_queue: Optional[_BatchQueue] = None
        self.__name__ = fn.__name__
        self.__doc__ = fn.__doc__

    def _make_queue(self) -> _BatchQueue:
        return _BatchQueue(
            self._fn, self._max, self._wait,
            max_queue_len=self._max_queue, shed_timeout_s=self._shed_timeout,
        )

    def _instance_queue(self, obj) -> _BatchQueue:
        q = obj.__dict__.get(self._queue_attr)
        if q is None:
            q = self._make_queue()
            obj.__dict__[self._queue_attr] = q
        return q

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self

        async def bound(item):
            return await self._instance_queue(obj).submit(obj, item)

        bound.__name__ = self.__name__
        bound._batch_queue = self._instance_queue(obj)
        return bound

    async def __call__(self, item):
        # Free-function form: one module-level queue.
        if self._free_queue is None:
            self._free_queue = self._make_queue()
        return await self._free_queue.submit(None, item)


def batch(_func=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01,
          max_queue_len: int = 0,
          shed_timeout_s: Optional[float] = None):
    """Decorate an `async def` taking a LIST of items (after self) so that
    concurrent single-item calls coalesce into one call of the underlying
    function. Callers invoke it with ONE item and await one result.

        class Model:
            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
            async def predict(self, inputs: list) -> list: ...
            async def __call__(self, request):
                return await self.predict(request)

    With `max_queue_len`, submits finding the queue at capacity shed
    immediately (RequestShedded -> 503 + Retry-After at the front door);
    with `shed_timeout_s`, members that waited past it shed individually at
    flush time instead of the whole batch timing out together.
    """
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    if batch_wait_timeout_s < 0:
        raise ValueError("batch_wait_timeout_s must be >= 0")
    if max_queue_len < 0:
        raise ValueError("max_queue_len must be >= 0 (0 = unbounded)")
    if shed_timeout_s is not None and shed_timeout_s < 0:
        raise ValueError("shed_timeout_s must be >= 0")

    def deco(fn):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an `async def` function")
        return _BatchWrapper(
            fn, max_batch_size, batch_wait_timeout_s,
            max_queue_len=max_queue_len, shed_timeout_s=shed_timeout_s,
        )

    return deco if _func is None else deco(_func)
