"""Serve public API: deployments, applications, run/shutdown.

Reference: `python/ray/serve/api.py` (`@serve.deployment`, `serve.run:460`)
and `_private/deployment_graph_build.py` (bound DAG -> deployments). A
`Deployment.bind(...)` builds an `Application` node; `serve.run` deploys the
graph bottom-up (bound children become `DeploymentHandle`s in the parent's
init args), marks the top node as ingress, and exposes it over HTTP.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.serve._private.common import (
    CONTROLLER_NAME,
    DEFAULT_HTTP_PORT,
    PROXY_NAME,
    AutoscalingConfig,
    DeploymentInfo,
)
from ray_tpu.serve.handle import DeploymentHandle

_VALID_DEPLOYMENT_OPTIONS = {
    "name",
    "num_replicas",
    "ray_actor_options",
    "autoscaling_config",
    "route_prefix",
    "max_concurrent_queries",
    "max_queued_requests",
    "user_config",
    "version",
}


class Application:
    """A bound deployment graph node (reference: `serve/deployment.py`
    `Application`/`BuiltApplication`)."""

    def __init__(self, deployment: "Deployment", args: Tuple, kwargs: Dict[str, Any]):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, target, options: Optional[Dict[str, Any]] = None):
        self._target = target
        opts = dict(options or {})
        for k in opts:
            if k not in _VALID_DEPLOYMENT_OPTIONS:
                raise ValueError(f"invalid deployment option: {k}")
        self._options = opts

    @property
    def name(self) -> str:
        return self._options.get("name") or self._target.__name__

    def options(self, **opts) -> "Deployment":
        merged = dict(self._options)
        merged.update(opts)
        return Deployment(self._target, merged)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"Deployment {self.name} cannot be called directly; deploy it with "
            "serve.run() and use the returned handle."
        )


def deployment(_target=None, **opts) -> Union[Deployment, Any]:
    """`@serve.deployment` decorator (bare or parameterized)."""
    if _target is not None:
        return Deployment(_target)

    def wrap(target):
        return Deployment(target, opts)

    return wrap


def ingress(asgi_app):
    """`@serve.ingress(app)`: mount an ASGI application (FastAPI/Starlette or
    any ASGI-3 callable) on a deployment class — HTTP requests route through
    the app's own router, streamed end-to-end (reference:
    `python/ray/serve/api.py:160`).

    Usage::

        app = SomeASGIFramework()

        @serve.deployment
        @serve.ingress(app)
        class Api:
            ...

    The decorated class (and its replicas) expose the app via
    `__serve_asgi_app__`; the HTTP proxy speaks ASGI to them.
    """
    if not callable(asgi_app):
        raise TypeError("serve.ingress expects an ASGI application callable")

    def wrap(cls):
        if not isinstance(cls, type):
            raise TypeError("@serve.ingress decorates a class")
        # staticmethod: instance access must yield the raw app callable, not
        # a bound method (which would shift the scope/receive/send args).
        cls.__serve_asgi_app__ = staticmethod(asgi_app)
        return cls

    return wrap


# ---------------------------------------------------------------- runtime state
_client: Dict[str, Any] = {}


def _get_controller(create: bool = True):
    from ray_tpu.serve._private.controller import ServeController

    if "controller" in _client:
        return _client["controller"]
    try:
        handle = ray_tpu.get_actor(CONTROLLER_NAME)
        from ray_tpu.actor import ActorHandle

        handle = ActorHandle(handle._actor_id, "ServeController")
    except ValueError:
        if not create:
            raise RuntimeError("Serve is not running (call serve.run/start first)")
        handle = (
            ray_tpu.remote(ServeController)
            # Threaded: each long-polling router/proxy parks in one call slot;
            # sized generously — parked threads are cheap, starved deploys are
            # not (large fleets: shard routers over per-node controllers).
            .options(
                name=CONTROLLER_NAME,
                num_cpus=0.1,
                max_concurrency=256,
                get_if_exists=True,
                # Serve outlives the driver that started it (reference: all
                # Serve system actors are detached); serve.shutdown() kills.
                lifetime="detached",
            )
            .remote()
        )
        ray_tpu.get(handle.__ray_ready__.remote())
    _client["controller"] = handle
    return handle


def _get_proxy(create: bool = True, port: int = DEFAULT_HTTP_PORT):
    from ray_tpu.serve._private.http_proxy import HTTPProxy

    if "proxy" in _client:
        return _client["proxy"]
    controller = _get_controller()
    try:
        handle = ray_tpu.get_actor(PROXY_NAME)
        from ray_tpu.actor import ActorHandle

        handle = ActorHandle(handle._actor_id, "HTTPProxy")
    except ValueError:
        if not create:
            return None
        if port == 0:
            # Ephemeral port: a crash-restart would rebind a DIFFERENT port
            # and strand every client that cached http_port() — keep the
            # explicit-start path (no auto-restart) for port=0.
            handle = (
                ray_tpu.remote(HTTPProxy)
                .options(
                    name=PROXY_NAME, num_cpus=0.1, get_if_exists=True,
                    lifetime="detached",
                )
                .remote(controller)
            )
            bound = ray_tpu.get(handle.start.remote(port=0))
        else:
            handle = (
                ray_tpu.remote(HTTPProxy)
                .options(
                    name=PROXY_NAME, num_cpus=0.1, get_if_exists=True,
                    lifetime="detached", max_restarts=10,
                )
                .remote(controller, port)
            )
            # Binding happened in __init__ (crash-restarts rebind the same
            # fixed port); a recorded bind failure surfaces here.
            err = ray_tpu.get(handle.start_error.remote())
            if err:
                raise RuntimeError(f"HTTP proxy failed to bind port {port}: {err}")
            bound = ray_tpu.get(handle.port.remote())
        _client["http_port"] = bound
    _client["proxy"] = handle
    return handle


def start(
    *,
    proxy_location: str = "HeadOnly",
    http_options: Optional[Dict[str, Any]] = None,
) -> None:
    """Start Serve system actors ahead of `serve.run` (reference:
    `serve.start`, `http_options={"location": "EveryNode"}`). With
    `proxy_location="EveryNode"` the CONTROLLER spawns and manages one HTTP
    proxy actor per cluster node — exactly like replicas (the reference's
    `http_state.py` fleet): each is registered in the head's service
    directory on bind, mirrors the shared routing table via the controller
    long poll, and is respawned/re-bound by the controller's reconcile loop;
    nodes that join later get a proxy automatically. Each binds its own
    port (`port=0` picks a free one — required when virtual nodes share one
    machine). `serve.proxy_ports()` lists them."""
    ray_tpu._private.worker._auto_init()
    opts = dict(http_options or {})
    location = opts.get("location", proxy_location)
    port = int(opts.get("port", DEFAULT_HTTP_PORT))
    controller = _get_controller()
    if location != "EveryNode":
        _get_proxy(create=True, port=port)
        return
    ray_tpu.get(controller.ensure_proxies.remote(port=0))
    _client["managed_proxies"] = True


def proxy_ports() -> Dict[str, int]:
    """node_id -> bound HTTP port for per-node (controller-managed) proxies
    (+ the default proxy under "head" when present)."""
    out: Dict[str, int] = {}
    if _client.get("managed_proxies") and "controller" in _client:
        try:
            proxies = ray_tpu.get(_client["controller"].get_proxies.remote())
            out.update({nid: p["port"] for nid, p in proxies.items()})
        except Exception:
            pass
    if "http_port" in _client:
        out["head"] = _client["http_port"]
    return out


def http_port() -> Optional[int]:
    if "http_port" in _client:
        return _client["http_port"]
    proxy = _get_proxy(create=False)
    if proxy is None:
        return None
    port = ray_tpu.get(proxy.port.remote())
    _client["http_port"] = port
    return port


# ------------------------------------------------------------------------- run
def _collect_apps(app: Application, out: List[Application]) -> None:
    """Post-order: children first, so handles exist before parents deploy."""
    for a in list(app.args) + list(app.kwargs.values()):
        if isinstance(a, Application):
            _collect_apps(a, out)
    if app not in out:
        out.append(app)


def run(
    target: Union[Application, Deployment],
    *,
    route_prefix: Optional[str] = "/",
    host: str = "127.0.0.1",
    port: int = DEFAULT_HTTP_PORT,
    _blocking_http: bool = True,
) -> DeploymentHandle:
    """Deploy an application (graph); returns a handle to the ingress."""
    from ray_tpu._private import usage

    usage.record_library_usage("serve")
    ray_tpu._private.worker._auto_init()
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects an Application, got {type(target)}")

    controller = _get_controller()
    order: List[Application] = []
    _collect_apps(target, order)
    routed_prefixes: List[str] = []
    for app in order:
        dep = app.deployment
        resolved_args = tuple(
            DeploymentHandle(a.deployment.name, controller)
            if isinstance(a, Application)
            else a
            for a in app.args
        )
        resolved_kwargs = {
            k: DeploymentHandle(v.deployment.name, controller)
            if isinstance(v, Application)
            else v
            for k, v in app.kwargs.items()
        }
        is_ingress = app is target
        info = DeploymentInfo(
            name=dep.name,
            blob=serialization.dumps(dep._target),
            init_args=resolved_args,
            init_kwargs=resolved_kwargs,
            num_replicas=int(dep._options.get("num_replicas", 1)),
            max_concurrent_queries=int(
                dep._options.get("max_concurrent_queries", 1)
            ),
            max_queued_requests=int(
                dep._options.get("max_queued_requests", 0)
            ),
            ray_actor_options=dep._options.get("ray_actor_options") or {},
            autoscaling_config=_coerce_autoscaling(
                dep._options.get("autoscaling_config")
            ),
            route_prefix=(
                dep._options.get("route_prefix", route_prefix) if is_ingress
                else dep._options.get("route_prefix")
            ),
            is_ingress=is_ingress,
            is_asgi=hasattr(dep._target, "__serve_asgi_app__"),
        )
        if info.route_prefix:
            # EVERY routed deployment in this run is awaited, not just the
            # ingress — a child with its own route_prefix is routable the
            # moment run() returns too.
            routed_prefixes.append(info.route_prefix)
        ray_tpu.get(controller.deploy.remote(info))
    if _blocking_http:
        _get_proxy(create=True, port=port)
    # Readiness barrier: replicas are already live (controller.deploy blocks
    # on __ray_ready__ per replica), but the route table reaches proxies via
    # an async long-poll push — returning before every proxy has the route
    # lets an immediate request 404 (reference: serve.run blocks until
    # deployments AND routes are ready, serve/api.py:460).
    for prefix in routed_prefixes:
        _wait_routes_live(prefix)
    return DeploymentHandle(target.deployment.name, controller)


def _wait_routes_live(prefix: str, timeout: float = 30.0) -> None:
    """Block until every responsive proxy (head + controller-managed) can
    route `prefix`. A proxy that never answers within the deadline (dead
    node, crash-looping restart) is skipped rather than failing the deploy —
    the app IS live on every proxy that can serve it (the controller's
    reconcile loop brings stragglers back)."""
    from ray_tpu.actor import ActorHandle

    named = [("head", h) for h in ([_client["proxy"]] if "proxy" in _client else [])]
    if _client.get("managed_proxies") and "controller" in _client:
        try:
            proxies = ray_tpu.get(_client["controller"].get_proxies.remote())
            named += [
                (nid, ActorHandle(p["actor_id"], "HTTPProxy"))
                for nid, p in proxies.items()
            ]
        except Exception:
            pass
    deadline = time.time() + timeout
    for nid, h in named:
        responded = False
        while True:
            try:
                if ray_tpu.get(h.has_route.remote(prefix)):
                    break
                responded = True
            except Exception:
                # Proxy mid-restart or dead: keep polling until the deadline.
                pass
            if time.time() > deadline:
                if responded:
                    # Reachable but still missing the route: a real push
                    # failure the caller must hear about.
                    raise TimeoutError(
                        f"route {prefix!r} was not live at proxy {nid} "
                        f"within {timeout}s"
                    )
                break
            time.sleep(0.05)


def _coerce_autoscaling(cfg) -> Optional[AutoscalingConfig]:
    if cfg is None or isinstance(cfg, AutoscalingConfig):
        return cfg
    if isinstance(cfg, dict):
        return AutoscalingConfig(**cfg)
    raise TypeError(f"invalid autoscaling_config: {cfg!r}")


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_controller(create=False))


def status() -> Dict[str, Any]:
    controller = _get_controller(create=False)
    return ray_tpu.get(controller.list_deployments.remote())


def delete(name: str) -> None:
    controller = _get_controller(create=False)
    ray_tpu.get(controller.delete_deployment.remote(name))


def shutdown() -> None:
    from ray_tpu.serve.handle import close_all_routers

    close_all_routers()
    if "controller" in _client:
        try:
            ray_tpu.get(_client["controller"].shutdown.remote())
            ray_tpu.kill(_client["controller"])
        except Exception:
            pass
    if "proxy" in _client:
        try:
            ray_tpu.kill(_client["proxy"])
        except Exception:
            pass
    # Controller-managed (EveryNode) proxies are killed by
    # controller.shutdown() above.
    _client.clear()
