"""Model multiplexing: many models per replica with LRU caching.

Reference: `python/ray/serve/api.py` `@serve.multiplexed` +
`serve.get_multiplexed_model_id()` (`_private/multiplex.py` — per-replica
LRU of loaded models keyed by the request's model id; the router prefers
replicas that already hold the model).

TPU-first rationale: one chip serves MANY fine-tuned variants (LoRA
adapters, per-tenant heads) — reloading weights per request wastes HBM
bandwidth; the LRU keeps hot variants resident and model-affinity routing
(see `handle.py Router.route`) sends a model's traffic back to the replica
that already paid its load cost.
"""

from __future__ import annotations

import contextvars
from collections import OrderedDict
from typing import Any, Dict, Optional

#: Reserved kwarg smuggling the model id through the replica call protocol
#: (popped by ServeReplica before user code sees kwargs).
MODEL_ID_KWARG = "_serve_multiplexed_model_id"
#: HTTP header carrying the model id through the proxy (reference name).
MODEL_ID_HEADER = "serve_multiplexed_model_id"

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """The model id of the current request ("" when none was sent).
    Reference: `serve.get_multiplexed_model_id`."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id)


def _reset_model_id(token) -> None:
    _model_id_ctx.reset(token)


async def _run_with_model_id(model_id: str, coro):
    """Drive a user coroutine with the model-id contextvar set. Run as ONE
    asyncio task so the set persists across every suspension of the user
    code (a task's context is stable for its whole life)."""
    token = _model_id_ctx.set(model_id)
    try:
        return await coro
    finally:
        _model_id_ctx.reset(token)


class _ModelCache:
    """Per-instance LRU of loaded models with single-flight loads."""

    def __init__(self, loader, self_obj, max_models: int):
        self._loader = loader
        self._self = self_obj
        self.max_models = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: Dict[str, Any] = {}  # model_id -> asyncio.Future

    def model_ids(self):
        return list(self._models)

    async def get(self, model_id: str):
        import asyncio

        if model_id in self._models:
            self._models.move_to_end(model_id)
            return self._models[model_id]
        pending = self._loading.get(model_id)
        if pending is not None:
            # Single-flight: concurrent requests for one model await the
            # same load instead of loading N copies.
            return await asyncio.shield(pending)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._loading[model_id] = fut
        try:
            if self._self is not None:
                model = await self._loader(self._self, model_id)
            else:
                model = await self._loader(model_id)
        except BaseException as e:  # noqa: BLE001 — incl. CancelledError:
            # the single-flight future MUST resolve or every waiter that
            # grabbed it hangs forever (streaming disconnects cancel loads).
            if not fut.done():
                fut.set_exception(e)
            # Consume the exception so an un-awaited future doesn't warn.
            fut.exception()
            raise
        finally:
            self._loading.pop(model_id, None)
        self._models[model_id] = model
        self._models.move_to_end(model_id)
        while len(self._models) > self.max_models:
            _, evicted = self._models.popitem(last=False)
            unload = getattr(evicted, "__serve_unload__", None)
            if callable(unload):
                try:
                    out = unload()
                    if asyncio.iscoroutine(out):
                        await out
                except Exception:  # noqa: BLE001 — eviction is best-effort
                    pass
        if not fut.done():
            fut.set_result(model)
        return model


class _MultiplexWrapper:
    """Descriptor form of @serve.multiplexed: each instance owns its cache."""

    def __init__(self, fn, max_num_models_per_replica: int):
        self._fn = fn
        self._max = max_num_models_per_replica
        self._cache_attr = f"__serve_multiplex_cache_{fn.__name__}__"
        self.__name__ = fn.__name__
        self.__doc__ = fn.__doc__

    def _cache_for(self, obj) -> _ModelCache:
        c = obj.__dict__.get(self._cache_attr)
        if c is None:
            c = _ModelCache(self._fn, obj, self._max)
            obj.__dict__[self._cache_attr] = c
        return c

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        cache = self._cache_for(obj)

        async def bound(model_id: Optional[str] = None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            if not model_id:
                raise ValueError(
                    "no model id: pass one explicitly or send the request "
                    f"with a multiplexed model id (header {MODEL_ID_HEADER} "
                    "or handle.options(multiplexed_model_id=...))"
                )
            return await cache.get(model_id)

        bound.__name__ = self.__name__
        bound._model_cache = cache
        return bound


def multiplexed(_func=None, *, max_num_models_per_replica: int = 3):
    """Decorate an `async def (self, model_id) -> model` loader: calls are
    LRU-cached per replica (capacity `max_num_models_per_replica`), loads are
    single-flight, and evicted models get `__serve_unload__()` if defined.

        class Multi:
            @serve.multiplexed(max_num_models_per_replica=4)
            async def get_model(self, model_id: str): ...
            async def __call__(self, request):
                model = await self.get_model()  # id from the request context
    """
    import inspect

    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def deco(fn):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.multiplexed requires an `async def` loader")
        return _MultiplexWrapper(fn, max_num_models_per_replica)

    return deco if _func is None else deco(_func)
