"""ray_tpu.serve: model serving on the actor substrate.

Reference: `python/ray/serve/` (P19 in SURVEY.md §2) — controller actor
reconciling replica actors (`controller.py:73`, `deployment_state.py:1009`),
HTTP proxy (`http_proxy.py:250`), power-of-two router (`router.py:263`),
deployment graph composition (`deployment_graph_build.py`), autoscaling
(`autoscaling_policy.py`).

TPU-serving note: a deployment whose replicas hold a jax model keeps params
device-resident in the replica process; requests batch naturally per replica
(one ordered queue), and replica count maps to chips via
`ray_actor_options={"num_tpus": ...}`.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    http_port,
    ingress,
    proxy_ports,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve._private.common import AutoscalingConfig, RequestShedded
from ray_tpu.serve._private.http_proxy import ProxyRequest

__all__ = [
    "batch",
    "get_multiplexed_model_id",
    "multiplexed",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "ProxyRequest",
    "RequestShedded",
    "delete",
    "deployment",
    "get_deployment_handle",
    "http_port",
    "ingress",
    "proxy_ports",
    "run",
    "shutdown",
    "start",
    "status",
]
