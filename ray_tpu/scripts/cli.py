"""Operational CLI, the analogue of `ray start/stop/status/list/timeline/...`
(reference: `python/ray/scripts/scripts.py` — `ray start:529`, `ray stop:1013`,
`ray microbenchmark`, `ray timeline`, state CLI `experimental/state/state_cli.py`).

Usage (via `python -m ray_tpu`):
  start --head [--port P] [--num-cpus N] [--num-tpus N]   start a head server
  start --address HOST:PORT [--num-cpus N] ...            start a node daemon
  stop                                                    stop processes this CLI started
  status [--address A]                                    cluster resource + entity rollup
  list {nodes,actors,tasks,objects} [--address A]
  timeline --output FILE [--address A]                    chrome://tracing dump
  microbenchmark                                          run bench_core
  job submit --entrypoint "python x.py" [--working-dir D] [--address A]
  job {status,logs,list,stop} ...

Connection resolution: --address flag, else RAY_TPU_ADDRESS env, else the
head this CLI started (recorded in ~/.ray_tpu/cli_state.json).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

STATE_FILE = os.path.expanduser("~/.ray_tpu/cli_state.json")


def _load_state() -> dict:
    try:
        with open(STATE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save_state(state: dict) -> None:
    os.makedirs(os.path.dirname(STATE_FILE), exist_ok=True)
    with open(STATE_FILE, "w") as f:
        json.dump(state, f, indent=2)


def _connect(ns):
    """init() against the resolved address (or error out with guidance)."""
    import ray_tpu

    address = getattr(ns, "address", None) or os.environ.get("RAY_TPU_ADDRESS")
    state = _load_state()
    if not address and state.get("head"):
        address = state["head"]["address"]
        os.environ.setdefault("RAY_TPU_AUTHKEY_HEX", state["head"]["authkey_hex"])
    if not address:
        sys.exit(
            "no cluster address: pass --address, set RAY_TPU_ADDRESS, or "
            "`python -m ray_tpu start --head` first"
        )
    ray_tpu.init(address=address)
    return ray_tpu


# ----------------------------------------------------------------- start/stop
def cmd_start(ns):
    from ray_tpu._private.launch import spawn_head, spawn_node_daemon

    state = _load_state()
    if ns.head:
        extra = []
        if ns.dashboard_port is not None:
            extra += ["--dashboard-port", str(ns.dashboard_port)]
        if ns.persist:
            extra += ["--persist", ns.persist]
        try:
            proc, info = spawn_head(
                port=ns.port, host=ns.host,
                num_cpus=ns.num_cpus, num_tpus=ns.num_tpus,
                resources=json.loads(ns.resources) if ns.resources else None,
                extra_args=tuple(extra),
            )
        except (TimeoutError, RuntimeError) as e:
            sys.exit(str(e))
        state["head"] = {"pid": proc.pid, **info}
        _save_state(state)
        print(f"head started: address={info['address']} pid={proc.pid}")
        if info.get("dashboard_port"):
            print(f"dashboard: http://{ns.host}:{info['dashboard_port']}")
        print(f"connect with: ray_tpu.init(address=\"{info['address']}\")  "
              f"[RAY_TPU_AUTHKEY_HEX={info['authkey_hex']}]")
    else:
        if not ns.address:
            sys.exit("start needs --head or --address HOST:PORT")
        head = state.get("head") or {}
        authkey = os.environ.get("RAY_TPU_AUTHKEY_HEX") or head.get("authkey_hex")
        shm_dir = ns.shm_dir or tempfile.mkdtemp(prefix="ray_tpu_node_")
        resources = json.loads(ns.resources) if ns.resources else {}
        if ns.num_cpus is not None:
            resources.setdefault("CPU", float(ns.num_cpus))
        if ns.num_tpus:
            resources.setdefault("TPU", float(ns.num_tpus))
        try:
            proc, node_id = spawn_node_daemon(
                ns.address, shm_dir=shm_dir, resources=resources, authkey_hex=authkey
            )
        except (TimeoutError, RuntimeError) as e:
            sys.exit(str(e))
        state.setdefault("daemons", []).append({"pid": proc.pid, "node_id": node_id})
        _save_state(state)
        print(f"node daemon started: node_id={node_id} pid={proc.pid}")


def cmd_stop(_ns):
    state = _load_state()
    stopped = 0
    for d in state.get("daemons", []):
        try:
            os.kill(d["pid"], signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    head = state.get("head")
    if head:
        try:
            os.kill(head["pid"], signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    _save_state({})
    print(f"stopped {stopped} process(es)")


# --------------------------------------------------------------------- state
def cmd_status(ns):
    _connect(ns)
    from ray_tpu.util import state as state_api

    print(json.dumps(state_api.summarize(), indent=2, default=str))


def cmd_list(ns):
    _connect(ns)
    from ray_tpu.util import state as state_api

    fn = {
        "nodes": state_api.list_nodes,
        "actors": state_api.list_actors,
        "tasks": state_api.list_tasks,
        "objects": state_api.list_objects,
    }[ns.what]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_timeline(ns):
    _connect(ns)
    from ray_tpu.util import state as state_api

    events = state_api.timeline(ns.output)
    print(f"wrote {len(events)} events to {ns.output}")


# ------------------------------------------------------------ introspection
def cmd_stack(ns):
    """`ray stack` analogue: all-thread stacks from every live process,
    each thread annotated with the task it is executing."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    dumps = state_api.stacks(ns.timeout)
    for key in sorted(dumps):
        d = dumps[key] or {}
        print(f"=== {key} (pid={d.get('pid')}, "
              f"transport={d.get('transport', 'inband')}) ===")
        if d.get("transport") == "unavailable":
            print(f"  unavailable: {d.get('error')}")
        elif d.get("transport") == "oob":
            print(d.get("raw", ""), end="")
        else:
            for th in d.get("threads", ()):
                task = f"  [task: {th['task']}]" if th.get("task") else ""
                print(f"--- thread {th.get('name')} "
                      f"(id={th.get('thread_id')}){task}")
                print(th.get("stack", ""), end="")
        print()


def cmd_memory(ns):
    """`ray memory` analogue: ownership/refcount attribution, top sites,
    leak suspects, and the store-dir byte join."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    s = state_api.memory_summary()
    if ns.json:
        print(json.dumps(s, indent=2, default=str))
        return
    print(f"objects: {s['num_objects']}  shm: {s['shm_bytes']} B  "
          f"inline: {s['inline_bytes']} B  spilled: {s['spilled_bytes']} B  "
          f"(gauge: {s['gauge_bytes']:.0f} B)")
    print("\ntop creation sites:")
    for site, agg in s["by_site"].items():
        print(f"  {site:40s} {agg['count']:>6} objs {agg['bytes']:>14} B")
    if s["leak_suspects"]:
        print("\nLEAK SUSPECTS (only dead processes reference these):")
        for o in s["leak_suspects"]:
            print(f"  {o['object_id']} {o['size']} B site={o['site']} "
                  f"holders={o['holders']}")
    scan = s["store_scan"]
    if scan.get("leaked"):
        print(f"\nLEAKED STORE BYTES ({scan['leaked_bytes']} B unreferenced "
              f"in {scan['dir']}):")
        for e in scan["leaked"]:
            print(f"  {e['path']} {e['bytes']} B ({e['kind']})")


def cmd_profile(ns):
    """Cluster-wide sampling profile; folded stacks to --output (flamegraph.pl
    / speedscope input) or stdout."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    res = state_api.profile(ns.duration, hz=ns.hz)
    text = res["flamegraph"]
    if ns.output:
        with open(ns.output, "w") as f:
            f.write(text + "\n")
        print(f"wrote {len(res['folded'])} folded stacks "
              f"({res['samples']} samples) to {ns.output}")
    else:
        print(text)


# ------------------------------------------------------------ observability
def cmd_events(ns):
    """Cluster event log: node lifecycle, worker crashes, scale decisions,
    Serve changes, alert fire/resolve (newest last)."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    events = state_api.list_cluster_events(
        limit=ns.limit, kind=ns.kind, severity=ns.severity
    )
    if ns.json:
        print(json.dumps(events, indent=2, default=str))
        return
    for e in events:
        stamp = time.strftime("%H:%M:%S", time.localtime(e["ts"]))
        extra = f"  {e['data']}" if e.get("data") else ""
        print(f"{stamp}  {e['severity']:<8} {e['kind']:<24} "
              f"[{e['source']}] {e['message']}{extra}")
    if not events:
        print("(no events)")


def cmd_series(ns):
    """Query the head's time-series store: counter rates, gauge levels, or
    histogram quantiles over time."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    res = state_api.query_series(
        ns.name,
        labels=json.loads(ns.labels) if ns.labels else None,
        since=time.time() - ns.window if ns.window else None,
        step=ns.step,
        agg=ns.agg,
        q=ns.q,
    )
    if ns.json:
        print(json.dumps(res, indent=2, default=str))
        return
    print(f"{res['name']} ({res['kind']}, step={res['step']:g}s)")
    for s in res["series"]:
        label = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
        print(f"  {{{label}}}")
        for ts, v in s["points"]:
            stamp = time.strftime("%H:%M:%S", time.localtime(ts))
            print(f"    {stamp}  {v if v is None else round(v, 6)}")
    if not res["series"]:
        print("  (no samples)")


def cmd_trace(ns):
    """End-to-end request traces: list recent ones, or show one trace's
    spans + critical-path attribution (`--trace-id`)."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    if ns.trace_id:
        t = state_api.get_trace(ns.trace_id)
        if ns.json:
            print(json.dumps(t, indent=2, default=str))
            return
        print(f"trace {t['trace_id']}  root={t['root']!r} "
              f"({t['root_kind']})  {t['duration_s'] * 1e3:.2f}ms  "
              f"status={t['status']}")
        by_id = {s["span_id"]: s for s in t["spans"]}

        def depth_of(s):
            d, p = 0, s.get("parent_id")
            while p in by_id and d < 32:
                d, p = d + 1, by_id[p].get("parent_id")
            return d

        t0 = min(s["start"] for s in t["spans"])
        for s in t["spans"]:
            pad = "  " * depth_of(s)
            dur = ((s.get("end") or s["start"]) - s["start"]) * 1e3
            print(f"  {pad}{s['name']} [{s['kind']}] "
                  f"+{(s['start'] - t0) * 1e3:.2f}ms {dur:.2f}ms "
                  f"{s['status']}")
        attr = t["attribution"]
        print(f"\nattribution ({attr['coverage'] * 100:.1f}% of "
              f"{attr['total_s'] * 1e3:.2f}ms wall):")
        for comp, secs in attr["components"].items():
            print(f"  {comp:<14} {secs * 1e3:>10.3f}ms")
        return
    traces = state_api.list_traces(ns.limit)
    if ns.json:
        print(json.dumps(traces, indent=2, default=str))
        return
    for t in traces:
        stamp = time.strftime("%H:%M:%S", time.localtime(t["start"]))
        tail = "  [tail-kept]" if t.get("tail_kept") else ""
        print(f"{stamp}  {t['trace_id']}  {t['duration_s'] * 1e3:>9.2f}ms  "
              f"{t['spans']:>3} spans  {t['status']:<5} "
              f"{t['root'] or '?'}{tail}")
    if not traces:
        print("(no traces recorded — is tracing enabled? "
              "RAY_TPU_TRACING=1 or tracing.enable())")


def cmd_latency(ns):
    """'Where does p95 actually go': per-component latency attribution over
    recent traces (state.latency_report)."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    rep = state_api.latency_report(ns.limit)
    if ns.json:
        print(json.dumps(rep, indent=2, default=str))
        return
    if not rep["traces"]:
        print("(no complete traces to attribute)")
        return
    p50 = rep["trace_p50_s"] or 0.0
    p95 = rep["trace_p95_s"] or 0.0
    print(f"latency report over {rep['traces']} trace(s): "
          f"p50={p50 * 1e3:.2f}ms p95={p95 * 1e3:.2f}ms "
          f"coverage={rep['coverage'] * 100:.1f}%")
    print(f"{'component':<14} {'total':>12} {'share':>7}")
    for comp, row in rep["components"].items():
        print(f"{comp:<14} {row['total_s'] * 1e3:>10.3f}ms "
              f"{row['share'] * 100:>6.1f}%")


def cmd_train(ns):
    """Training-gang goodput ledgers: wall time split into productive vs
    badput buckets, current skew, and the named straggler per gang."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    rep = state_api.training_report(ns.gang)
    if ns.json:
        print(json.dumps(rep, indent=2, default=str))
        return
    gangs = rep["gangs"]
    if not gangs:
        print("(no training gangs — is enable_metrics on?)")
        return
    for gang_id, g in sorted(gangs.items()):
        wall = g.get("wall_s", 0.0) or 0.0
        print(f"gang {gang_id}  [{g.get('status', '?')}]  "
              f"world_size={g.get('world_size', '?')}  steps={g.get('steps', 0)}  "
              f"failures={g.get('failures', 0)}  "
              f"resizes={g.get('resizes', 0)}")
        print(f"  wall {wall:.2f}s  goodput {g.get('goodput_frac', 0.0) * 100:.1f}%  "
              f"coverage {g.get('coverage', 0.0) * 100:.1f}%")
        last_resize = g.get("last_resize")
        if last_resize:
            print(f"  last resize: {last_resize.get('old_world')} -> "
                  f"{last_resize.get('new_world')} "
                  f"({last_resize.get('direction')}, "
                  f"{last_resize.get('reason')}; "
                  f"{last_resize.get('resize_s', 0.0):.2f}s, resumed from "
                  f"{last_resize.get('ckpt_source')} checkpoint)")
        if g.get("proactive_checkpoints"):
            print(f"  proactive checkpoints: {g['proactive_checkpoints']} "
                  f"(SUSPECT-triggered stash fetches)")
        for bucket, secs in (g.get("buckets") or {}).items():
            share = secs / wall * 100 if wall > 0 else 0.0
            print(f"    {bucket:<16} {secs:>10.3f}s {share:>6.1f}%")
        straggler = g.get("straggler")
        if straggler:
            print(f"  straggler: rank {straggler['rank']} "
                  f"(dominant phase {straggler['phase']}, "
                  f"skew {straggler['skew_s']:.3f}s; "
                  f"current skew {g.get('skew_s', 0.0):.3f}s)")


def _render_top(state_api, iteration: int) -> str:
    """One frame of `ray_tpu top`, built entirely on the query/state APIs.
    Degrades gracefully when the obs layer is off (shows a notice instead
    of rates)."""
    now = time.time()
    lines = [f"ray_tpu top — {time.strftime('%H:%M:%S')} "
             f"(refresh #{iteration})", ""]
    summary = state_api.summarize()

    def last_rate(metric, labels=None, agg="sum"):
        try:
            res = state_api.query_series(
                metric, labels=labels, since=now - 15, step=5.0, agg=agg
            )
        except Exception:  # noqa: BLE001 — metrics off / head gone
            return None
        pts = [p for s in res["series"] for p in s["points"]
               if p[1] is not None]
        return pts[-1][1] if pts else None

    tasks_s = last_rate("ray_tpu_scheduler_tasks_dispatched_total")
    queue = last_rate("ray_tpu_scheduler_pending_tasks")
    lines.append(
        f"tasks/s: {tasks_s if tasks_s is None else round(tasks_s, 1)}    "
        f"queue depth: {queue if queue is None else int(queue)}    "
        f"tasks by state: {summary['tasks_by_state']}"
    )
    lines.append(
        f"resources: {summary['available_resources']} free of "
        f"{summary['cluster_resources']}    objects: {summary['objects']}"
    )
    lines.append("")
    lines.append("nodes:")
    for n in state_api.list_nodes():
        lines.append(
            f"  {n['node_id'][:8]}  health={n['health']:<8} "
            f"workers={n['num_workers']:<3} alive={n['alive']}"
        )
    rps = last_rate("ray_tpu_serve_proxy_requests_total")
    shed = last_rate("ray_tpu_serve_shed_total")
    p95 = last_rate("ray_tpu_serve_route_wait_p95_s", agg="max")
    if any(v is not None for v in (rps, shed, p95)):
        lines.append("")
        lines.append(
            f"serve: rps={rps if rps is None else round(rps, 1)}  "
            f"route-wait p95="
            f"{p95 if p95 is None else round(p95 * 1000, 1)}ms  "
            f"shed/s={shed if shed is None else round(shed, 1)}"
        )
    try:
        alerts = state_api.list_alerts()
    except Exception:  # noqa: BLE001
        alerts = []
    firing = [a for a in alerts if a["state"] == "firing"]
    lines.append("")
    if firing:
        lines.append("ALERTS FIRING:")
        for a in firing:
            lines.append(
                f"  !! {a['name']} ({a['severity']}): {a['summary']} "
                f"[value={a['value']}, threshold {a['op']} "
                f"{a['threshold']:g}]"
            )
    elif alerts:
        lines.append(f"alerts: {len(alerts)} rule(s), none firing")
    else:
        lines.append("alerts: (metrics disabled)")
    return "\n".join(lines)


def cmd_top(ns):
    """Live refreshing cluster view (htop analogue) on the query API."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    i = 0
    try:
        while True:
            i += 1
            frame = _render_top(state_api, i)
            if not ns.no_clear:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(frame, flush=True)
            if ns.iterations and i >= ns.iterations:
                break
            time.sleep(ns.interval)
    except KeyboardInterrupt:
        pass


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _render_jobs(state_api, iteration: int) -> str:
    """One frame of `ray_tpu jobs`: the tenant ledger as a top-like table —
    who is using the cluster right now, at what rate, and who is starving."""
    now = time.time()
    jobs = state_api.list_jobs()

    def last_rate(metric, job, agg="sum", q=None):
        try:
            res = state_api.query_series(
                metric, labels={"job": job}, since=now - 15, step=5.0,
                agg=agg, q=q,
            )
        except Exception:  # noqa: BLE001 — metrics off / head gone
            return None
        pts = [p for s in res["series"] for p in s["points"]
               if p[1] is not None]
        return pts[-1][1] if pts else None

    try:
        alerts = state_api.list_alerts()
    except Exception:  # noqa: BLE001
        alerts = []
    firing = [a for a in alerts if a["state"] == "firing"]
    # The job rules aggregate across tenants (agg=max), so attribute a
    # firing rule to the jobs whose own value crosses its threshold.
    starve_thresh = next((a["threshold"] for a in firing
                          if a["name"] == "job_starved"), None)
    runaway_thresh = next((a["threshold"] for a in firing
                           if a["name"] == "job_runaway_object_bytes"), None)

    lines = [f"ray_tpu jobs — {time.strftime('%H:%M:%S')} "
             f"(refresh #{iteration})", ""]
    hdr = (f"{'JOB':<10} {'STATE':<9} {'DRIVER':<18} {'CPU-S/S':>8} "
           f"{'TASKS/S':>8} {'QW-P95':>8} {'OBJ':>9} {'XFER':>9} "
           f"{'SERVE':>6}  ALERTS")
    lines.append(hdr)
    for j in jobs:
        t = j.get("totals") or {}
        job = j["job"]
        live = j.get("state") == "LIVE"
        cpu_rate = last_rate("ray_tpu_job_cpu_seconds_total", job) if live else None
        task_rate = last_rate("ray_tpu_job_tasks_total", job) if live else None
        qw_p95 = last_rate("ray_tpu_job_queue_wait_seconds", job,
                           agg="max", q=0.95) if live else None
        names = []
        if (starve_thresh is not None and qw_p95 is not None
                and qw_p95 > starve_thresh):
            names.append("job_starved")
        if (runaway_thresh is not None
                and float(t.get("object_bytes") or 0) > runaway_thresh):
            names.append("job_runaway_object_bytes")
        alert_names = ",".join(names) or "-"
        lines.append(
            f"{job:<10} {j.get('state', ''):<9} "
            f"{str(j.get('driver') or '')[:18]:<18} "
            f"{'-' if cpu_rate is None else format(cpu_rate, '.2f'):>8} "
            f"{'-' if task_rate is None else format(task_rate, '.1f'):>8} "
            f"{'-' if qw_p95 is None else format(qw_p95, '.2f'):>8} "
            f"{_fmt_bytes(t.get('object_bytes')):>9} "
            f"{_fmt_bytes(t.get('transfer_bytes')):>9} "
            f"{t.get('serve_requests', 0):>6}  {alert_names}"
        )
    if not jobs:
        lines.append("(no jobs)")
    lines.append("")
    if firing:
        lines.append("ALERTS FIRING:")
        for a in firing:
            lines.append(f"  !! {a['name']} ({a['severity']}): {a['summary']}")
    else:
        lines.append(f"alerts: {len(alerts)} rule(s), none firing")
    return "\n".join(lines)


def cmd_jobs(ns):
    """Live per-job accounting view (`ray_tpu jobs`): cpu-s rate, tasks/s,
    queue-wait p95, object/transfer bytes, serve requests, firing alerts."""
    _connect(ns)
    from ray_tpu.util import state as state_api

    if ns.json:
        print(json.dumps(state_api.job_report(ns.job) if ns.job
                         else state_api.list_jobs(), indent=2, default=str))
        return
    if ns.job:
        print(json.dumps(state_api.job_report(ns.job), indent=2, default=str))
        return
    i = 0
    try:
        while True:
            i += 1
            frame = _render_jobs(state_api, i)
            if not ns.no_clear:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(frame, flush=True)
            if ns.iterations and i >= ns.iterations:
                break
            time.sleep(ns.interval)
    except KeyboardInterrupt:
        pass


def cmd_microbenchmark(_ns):
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.path.insert(0, repo_root)
    import bench_core

    bench_core.main()


# ---------------------------------------------------------------------- jobs
def cmd_job(ns):
    _connect(ns)
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    if ns.job_cmd == "submit":
        renv = {}
        if ns.working_dir:
            renv["working_dir"] = ns.working_dir
        job_id = client.submit_job(entrypoint=ns.entrypoint, runtime_env=renv or None)
        print(job_id)
        if ns.wait:
            status = client.wait_until_finished(job_id, timeout=ns.timeout)
            print(status)
            print(client.get_job_logs(job_id), end="")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif ns.job_cmd == "status":
        print(client.get_job_status(ns.job_id))
    elif ns.job_cmd == "logs":
        print(client.get_job_logs(ns.job_id), end="")
    elif ns.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2))
    elif ns.job_cmd == "stop":
        print("stopped" if client.stop_job(ns.job_id) else "not running")


# ---------------------------------------------------------------------- main
def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head server or node daemon")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="head address (node-daemon mode)")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--resources", help="JSON resource map")
    sp.add_argument("--shm-dir")
    sp.add_argument("--dashboard-port", type=int, default=None)
    sp.add_argument("--persist", help="GCS persistence file (head mode)")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop processes started by this CLI")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster rollup")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster entities")
    sp.add_argument("what", choices=["nodes", "actors", "tasks", "objects"])
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("timeline", help="dump chrome://tracing timeline")
    sp.add_argument("--output", default="timeline.json")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("stack", help="all-thread stack dump of every live process")
    sp.add_argument("--timeout", type=float, default=None,
                    help="per-process reply deadline before the out-of-band "
                         "faulthandler fallback")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("memory", help="object ownership/refcount attribution")
    sp.add_argument("--json", action="store_true", help="raw JSON output")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("profile", help="cluster-wide sampling profile")
    sp.add_argument("--duration", type=float, default=1.0)
    sp.add_argument("--hz", type=float, default=None)
    sp.add_argument("--output", help="write folded stacks to this file")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("events", help="cluster event log (node/worker/serve/"
                                       "autoscaler/alert transitions)")
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--kind", help="filter by event kind")
    sp.add_argument("--severity", help="filter by severity")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("series", help="query the head time-series store")
    sp.add_argument("name", help="metric name (e.g. ray_tpu_serve_shed_total)")
    sp.add_argument("--labels", help="JSON tag filter, e.g. '{\"app\":\"f\"}'")
    sp.add_argument("--window", type=float, default=60.0,
                    help="lookback seconds (0 = full retention)")
    sp.add_argument("--step", type=float, default=None)
    sp.add_argument("--agg", default="sum", choices=["sum", "max", "avg"])
    sp.add_argument("--q", type=float, default=None,
                    help="histogram quantile (e.g. 0.95)")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_series)

    sp = sub.add_parser("trace", help="end-to-end request traces "
                                      "(list, or one trace's critical path)")
    sp.add_argument("--trace-id", help="show one trace's spans + attribution")
    sp.add_argument("--limit", type=int, default=50)
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("latency", help="per-component latency attribution "
                                        "over recent traces")
    sp.add_argument("--limit", type=int, default=200)
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_latency)

    sp = sub.add_parser("train", help="training-gang goodput ledgers "
                                      "(phase split, straggler, badput)")
    sp.add_argument("--gang", help="one gang id (default: all gangs)")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_train)

    sp = sub.add_parser("top", help="live refreshing cluster view")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--iterations", type=int, default=0,
                    help="stop after N frames (0 = until Ctrl-C)")
    sp.add_argument("--no-clear", action="store_true",
                    help="append frames instead of clearing the screen")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("jobs", help="live per-job accounting view "
                                     "(who is using the cluster)")
    sp.add_argument("--job", help="one job's full ledger report (JSON)")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--iterations", type=int, default=0,
                    help="stop after N frames (0 = until Ctrl-C)")
    sp.add_argument("--no-clear", action="store_true",
                    help="append frames instead of clearing the screen")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("microbenchmark", help="run the core microbenchmark")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("job", help="job submission")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--entrypoint", required=True)
    j.add_argument("--working-dir")
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("--address")
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("job_id")
        j.add_argument("--address")
    j = jsub.add_parser("list")
    j.add_argument("--address")
    sp.set_defaults(fn=cmd_job)

    ns = p.parse_args(argv)
    ns.fn(ns)


if __name__ == "__main__":
    main()
