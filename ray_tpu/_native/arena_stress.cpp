// Thread-sanitizer stress harness for shm_arena.cpp (VERDICT r2/r3:
// sanitizer pass on the robust-mutex + coalescing allocator).
//
// N threads hammer one arena with alloc/write/verify/free cycles of random
// sizes. Each allocation is filled with a pattern derived from its offset
// and re-verified before free — catching overlapping allocations (allocator
// races) as data corruption, while TSAN catches any unsynchronized access
// to the header/block table.
//
// Build + run (tests/test_arena_stress.py does this):
//   g++ -O1 -g -fsanitize=thread -pthread arena_stress.cpp -o arena_stress
//   TSAN_OPTIONS=halt_on_error=1 ./arena_stress /dev/shm/arena_tsan 200
//
// The harness exits 0 iff every verify passed and TSAN found no race.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <pthread.h>
#include <unistd.h>
#include <vector>

extern "C" {
int arena_create(const char* path, uint64_t capacity);
void* arena_attach(const char* path);
void arena_detach(void* handle);
uint64_t arena_alloc(void* handle, uint64_t size);
int arena_free(void* handle, uint64_t offset);
uint64_t arena_used(void* handle);
uint8_t* arena_base(void* handle);
}

static const uint64_t CAPACITY = 64ull << 20;  // 64MB arena
static int g_iters = 200;
static const char* g_path = nullptr;
static volatile int g_failed = 0;

static void fill(uint8_t* p, uint64_t n, uint64_t seed) {
  for (uint64_t i = 0; i < n; i++) p[i] = (uint8_t)((seed + i) * 2654435761u >> 24);
}

static int verify(const uint8_t* p, uint64_t n, uint64_t seed) {
  for (uint64_t i = 0; i < n; i++)
    if (p[i] != (uint8_t)((seed + i) * 2654435761u >> 24)) return 0;
  return 1;
}

static void* worker(void* arg) {
  long tid = (long)(intptr_t)arg;
  void* h = arena_attach(g_path);
  if (!h) { g_failed = 1; return nullptr; }
  uint8_t* base = arena_base(h);
  unsigned int rng = 0x9e3779b9u ^ (unsigned)tid;
  std::vector<std::pair<uint64_t, uint64_t>> held;  // (offset, size)
  for (int it = 0; it < g_iters && !g_failed; it++) {
    rng = rng * 1103515245u + 12345u;
    uint64_t size = 64 + (rng % (512 * 1024));
    uint64_t off = arena_alloc(h, size);
    if (off != 0) {
      fill(base + off, size, off ^ tid);
      held.emplace_back(off, size);
    }
    // Free roughly half the time (and always when the arena pushed back),
    // verifying the pattern survived neighboring allocations.
    if ((!held.empty() && (rng & 1)) || (off == 0 && !held.empty())) {
      rng = rng * 1103515245u + 12345u;
      size_t idx = rng % held.size();
      auto [o, s] = held[idx];
      if (!verify(base + o, s, o ^ tid)) {
        fprintf(stderr, "CORRUPTION tid=%ld off=%llu size=%llu\n", tid,
                (unsigned long long)o, (unsigned long long)s);
        g_failed = 1;
      }
      if (arena_free(h, o) != 0) {
        fprintf(stderr, "BAD FREE tid=%ld off=%llu\n", tid, (unsigned long long)o);
        g_failed = 1;
      }
      held.erase(held.begin() + idx);
    }
  }
  for (auto [o, s] : held) {
    if (!verify(base + o, s, o ^ tid)) g_failed = 1;
    arena_free(h, o);
  }
  arena_detach(h);
  return nullptr;
}

int main(int argc, char** argv) {
  if (argc < 2) { fprintf(stderr, "usage: %s <path> [iters]\n", argv[0]); return 2; }
  g_path = argv[1];
  if (argc > 2) g_iters = atoi(argv[2]);
  unlink(g_path);
  if (arena_create(g_path, CAPACITY) != 0) { fprintf(stderr, "create failed\n"); return 2; }
  const int NTHREADS = 8;
  pthread_t ts[NTHREADS];
  for (long i = 0; i < NTHREADS; i++)
    pthread_create(&ts[i], nullptr, worker, (void*)(intptr_t)i);
  for (int i = 0; i < NTHREADS; i++) pthread_join(ts[i], nullptr);
  // All held allocations were freed: the arena must be (near-)empty again.
  void* h = arena_attach(g_path);
  uint64_t used = arena_used(h);
  arena_detach(h);
  unlink(g_path);
  if (g_failed) { fprintf(stderr, "FAILED\n"); return 1; }
  printf("ok: %d threads x %d iters, residual used=%llu\n", NTHREADS, g_iters,
         (unsigned long long)used);
  return used == 0 ? 0 : 1;
}
