"""Native runtime components (C++): built on demand with g++, bound via
ctypes (pybind11 is not in this environment — task constraints), with a pure-
Python fallback when no toolchain exists.

Current components:
 - shm_arena: process-shared object-store arena allocator (plasma's
   dlmalloc-over-shm redesigned without a store process; see shm_arena.cpp).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_SRC_DIR, "libshm_arena.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

# Stale-binary guard markers: each built .so embeds
# "<marker><sha256-of-its-source>\0" (see the #define stanzas in the C
# sources), so source<->binary drift is detectable by reading the binary —
# no dlopen needed. devtools/verify and tools/check.sh use the same scheme.
ARENA_HASH_MARKER = b"RAY_TPU_ARENA_SRC_SHA256="
WIRE_HASH_MARKER = b"RAY_TPU_WIRE_SRC_SHA256="


def source_sha256(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()
    except OSError:
        return None


def embedded_source_hash(lib_path: str, marker: bytes) -> Optional[str]:
    """The source hash stamped into a built .so, or None when the binary is
    missing or predates the stamp (treated as stale by callers)."""
    try:
        with open(lib_path, "rb") as fh:
            data = fh.read()
    except OSError:
        return None
    i = data.find(marker)
    if i < 0:
        return None
    i += len(marker)
    end = data.find(b"\x00", i)
    if end < 0:
        return None
    try:
        return data[i:end].decode("ascii")
    except UnicodeDecodeError:
        return None


def _binary_is_current(lib_path: str, marker: bytes, src_path: str) -> bool:
    src = source_sha256(src_path)
    return src is not None and embedded_source_hash(lib_path, marker) == src


def _build() -> bool:
    src = os.path.join(_SRC_DIR, "shm_arena.cpp")
    # pid-unique tmp + atomic replace: concurrent first-use builds from many
    # worker processes each publish a COMPLETE .so (last writer wins).
    tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f'-DARENA_SRC_SHA256="{source_sha256(src)}"',
        "-o", tmp, src, "-lpthread",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        return False
    os.replace(tmp, _LIB_PATH)
    return True


def load_arena_lib() -> Optional[ctypes.CDLL]:
    """The compiled arena library, building it on first use; None when no
    toolchain is available (callers fall back to per-object file segments)."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _build_lock:
        if _lib is not None:
            return _lib
        # Source hash, not mtime, decides staleness: git checkouts give
        # source and binary arbitrary mtime order, and a committed .so from
        # a drifted source must rebuild regardless of timestamps.
        if not _binary_is_current(
            _LIB_PATH, ARENA_HASH_MARKER, os.path.join(_SRC_DIR, "shm_arena.cpp")
        ):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            # A prebuilt .so from another machine can be unloadable here
            # (e.g. newer-glibc symbols). The source is authoritative:
            # rebuild once for THIS toolchain and retry before giving up.
            if not _build():
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError:
                _build_failed = True
                return None
        lib.arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.arena_create.restype = ctypes.c_int
        lib.arena_attach.argtypes = [ctypes.c_char_p]
        lib.arena_attach.restype = ctypes.c_void_p
        lib.arena_detach.argtypes = [ctypes.c_void_p]
        lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.arena_alloc.restype = ctypes.c_uint64
        lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.arena_free.restype = ctypes.c_int
        for name in ("arena_used", "arena_capacity", "arena_high_water", "arena_map_size"):
            fn = getattr(lib, name)
            fn.argtypes = [ctypes.c_void_p]
            fn.restype = ctypes.c_uint64
        lib.arena_base.argtypes = [ctypes.c_void_p]
        lib.arena_base.restype = ctypes.c_void_p
        _lib = lib
        return _lib


class Arena:
    """Python view of one attached arena mapping."""

    def __init__(self, path: str, create_capacity: Optional[int] = None):
        lib = load_arena_lib()
        if lib is None:
            raise RuntimeError("native arena library unavailable (no g++?)")
        self._lib = lib
        self.path = path
        if create_capacity is not None and not os.path.exists(path):
            rc = lib.arena_create(path.encode(), create_capacity)
            if rc != 0:
                raise OSError(-rc, f"arena_create failed for {path}")
        self._h = lib.arena_attach(path.encode())
        if not self._h:
            raise OSError(f"arena_attach failed for {path}")
        size = lib.arena_map_size(self._h)
        base = lib.arena_base(self._h)
        # ctypes arrays report format "<B", which memoryview ops reject;
        # cast() to plain "B" makes slices read/writable like bytes.
        self._mem = (ctypes.c_ubyte * size).from_address(base)
        self._view = memoryview(self._mem).cast("B")

    def alloc(self, size: int) -> int:
        """Payload offset, or 0 when the arena is full."""
        return self._lib.arena_alloc(self._h, size)

    def free(self, offset: int) -> None:
        self._lib.arena_free(self._h, offset)

    def view(self, offset: int, length: int) -> memoryview:
        """Zero-copy view of [offset, offset+length)."""
        return self._view[offset:offset + length]

    @property
    def used(self) -> int:
        return self._lib.arena_used(self._h)

    @property
    def capacity(self) -> int:
        return self._lib.arena_capacity(self._h)

    def detach(self) -> None:
        if self._h:
            # The ctypes view must die before munmap; drop our references.
            self._view = None
            self._mem = None
            self._lib.arena_detach(self._h)
            self._h = None


def available() -> bool:
    return load_arena_lib() is not None


# --------------------------------------------------------------------------
# wire_native: the control-plane codec (a real CPython extension, not a
# ctypes lib — per-call ctypes marshalling would eat the win on sub-
# microsecond pack/unpack calls). Same on-demand build-and-atomic-replace
# flow as the arena; ray_tpu/_private/wire.py falls back to its pure-Python
# codec when this returns None.
# --------------------------------------------------------------------------
_WIRE_SRC = os.path.join(_SRC_DIR, "wire_native.c")
_WIRE_LIB = os.path.join(_SRC_DIR, "wire_native.so")
_wire_mod = None
_wire_failed = False
_wire_lock = threading.Lock()


def _build_wire() -> bool:
    import sysconfig

    include = sysconfig.get_paths().get("include")
    if not include or not os.path.exists(os.path.join(include, "Python.h")):
        return False
    tmp = f"{_WIRE_LIB}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-I", include,
        f'-DWIRE_SRC_SHA256="{source_sha256(_WIRE_SRC)}"',
        "-o", tmp, _WIRE_SRC,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        return False
    os.replace(tmp, _WIRE_LIB)
    return True


def load_wire_module():
    """The wire_native extension module, building it on first use; None when
    no toolchain / headers are available (callers use the Python codec)."""
    global _wire_mod, _wire_failed
    if _wire_mod is not None:
        return _wire_mod
    if _wire_failed:
        return None
    with _wire_lock:
        if _wire_mod is not None:
            return _wire_mod
        if not _binary_is_current(_WIRE_LIB, WIRE_HASH_MARKER, _WIRE_SRC):
            if not _build_wire():
                _wire_failed = True
                return None
        def _try_load():
            import importlib.machinery
            import importlib.util

            try:
                loader = importlib.machinery.ExtensionFileLoader(
                    "ray_tpu._native.wire_native", _WIRE_LIB
                )
                spec = importlib.util.spec_from_file_location(
                    "ray_tpu._native.wire_native", _WIRE_LIB, loader=loader
                )
                mod = importlib.util.module_from_spec(spec)
                loader.exec_module(mod)
                return mod
            except (ImportError, OSError):
                return None

        mod = _try_load()
        if mod is None:
            # A prebuilt .so from another machine/interpreter: rebuild once
            # for THIS toolchain (source is authoritative) and retry.
            if not _build_wire():
                _wire_failed = True
                return None
            mod = _try_load()
            if mod is None:
                _wire_failed = True
                return None
        _wire_mod = mod
        return _wire_mod
