// Shared-memory arena allocator: the native core of the node object store.
//
// The reference's plasma store runs dlmalloc over one big shm mapping inside
// a C++ store process (/root/reference/src/ray/object_manager/plasma/
// dlmalloc.cc, shared_memory.cc). This build keeps plasma's key property —
// one mapping, offset-addressed allocations, zero-copy readers — without a
// store *process*: the allocator state lives IN the shared memory itself,
// guarded by a process-shared robust mutex, so every worker on the node
// allocates/frees directly (no socket round-trip per object, no per-object
// file create/unlink).
//
// Layout:  [ArenaHeader | Block | payload | Block | payload | ...]
// Blocks form an address-ordered implicit list (size + free flag); free uses
// next-block coalescing; allocation is first-fit with split. Offsets returned
// to Python are payload offsets relative to the mapping base.
//
// Crash safety: the mutex is PTHREAD_MUTEX_ROBUST — if a worker dies while
// holding it, the next locker gets EOWNERDEAD, marks the state consistent,
// and continues (allocation metadata is only mutated under the lock, and each
// mutation is a couple of word writes; worst case a crash leaks one block,
// which the control plane's refcounting will free again).
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

// Source-hash stamp (stale-binary guard): the build flow passes
// -DARENA_SRC_SHA256="<hex>" with the sha256 of this file; the marker string
// makes the hash greppable from the binary without loading it, and
// arena_source_hash() exposes it to the loader for self-heal rebuilds.
#ifndef ARENA_SRC_SHA256
#define ARENA_SRC_SHA256 "unknown"
#endif
__attribute__((used)) static const char arena_src_marker[] =
    "RAY_TPU_ARENA_SRC_SHA256=" ARENA_SRC_SHA256;

namespace {

constexpr uint64_t kMagic = 0x52415954505541ULL;  // "RAYTPUA"
constexpr uint64_t kAlign = 64;                   // match python store alignment

struct ArenaHeader {
  uint64_t magic;
  uint64_t capacity;        // payload region size (bytes after header)
  uint64_t used;            // currently allocated payload bytes
  uint64_t high_water;      // max used ever
  pthread_mutex_t lock;     // process-shared, robust
};

struct Block {
  uint64_t size;            // payload size of this block
  uint64_t free;            // 1 = free
};

constexpr uint64_t kHeaderSize = (sizeof(ArenaHeader) + kAlign - 1) & ~(kAlign - 1);
constexpr uint64_t kBlockSize = (sizeof(Block) + kAlign - 1) & ~(kAlign - 1);

struct Handle {
  uint8_t* base;
  uint64_t map_size;
};

inline ArenaHeader* header(Handle* h) {
  return reinterpret_cast<ArenaHeader*>(h->base);
}

inline Block* first_block(Handle* h) {
  return reinterpret_cast<Block*>(h->base + kHeaderSize);
}

inline Block* next_block(Handle* h, Block* b) {
  uint8_t* p = reinterpret_cast<uint8_t*>(b) + kBlockSize + b->size;
  if (p >= h->base + h->map_size) return nullptr;
  return reinterpret_cast<Block*>(p);
}

int lock_arena(ArenaHeader* hd) {
  int rc = pthread_mutex_lock(&hd->lock);
  if (rc == EOWNERDEAD) {
    // Previous owner died mid-critical-section: adopt and repair.
    pthread_mutex_consistent(&hd->lock);
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

// Create (or overwrite) an arena file of `capacity` payload bytes.
// Returns 0 on success.
int arena_create(const char* path, uint64_t capacity) {
  capacity = (capacity + kAlign - 1) & ~(kAlign - 1);
  uint64_t total = kHeaderSize + kBlockSize + capacity;
  int fd = open(path, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return -errno;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    int e = errno; close(fd); return -e;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;

  auto* hd = reinterpret_cast<ArenaHeader*>(mem);
  hd->magic = kMagic;
  hd->capacity = capacity;
  hd->used = 0;
  hd->high_water = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hd->lock, &attr);
  pthread_mutexattr_destroy(&attr);

  auto* b = reinterpret_cast<Block*>(reinterpret_cast<uint8_t*>(mem) + kHeaderSize);
  b->size = capacity;
  b->free = 1;

  munmap(mem, total);
  return 0;
}

// Attach to an existing arena; returns an opaque handle (NULL on failure).
void* arena_attach(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  // A truncated/empty file cannot hold even the header + first block: mapping
  // it and dereferencing the header would read past EOF (SIGBUS on the last
  // partial page). Validate BEFORE touching the mapping.
  if (static_cast<uint64_t>(st.st_size) < kHeaderSize + kBlockSize) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hd = reinterpret_cast<ArenaHeader*>(mem);
  // Reject a header whose claimed capacity exceeds the real mapping: every
  // block-walk bound derives from map_size, but used/capacity accounting
  // trusts the header, and a corrupt capacity would let a split carve blocks
  // past EOF on a file that shrank underneath us. Compare by SUBTRACTION:
  // `kHeaderSize + kBlockSize + capacity` wraps for a hostile capacity near
  // 2^64 (unsigned wrap is defined behavior — UBSan stays silent) and would
  // step right around this check.
  if (hd->magic != kMagic ||
      hd->capacity > static_cast<uint64_t>(st.st_size) - kHeaderSize - kBlockSize) {
    munmap(mem, st.st_size);
    return nullptr;
  }
  auto* h = new Handle{reinterpret_cast<uint8_t*>(mem), static_cast<uint64_t>(st.st_size)};
  return h;
}

void arena_detach(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  if (!h) return;
  munmap(h->base, h->map_size);
  delete h;
}

// Allocate `size` payload bytes; returns the payload offset from the mapping
// base, or 0 on failure (offset 0 is inside the header, never a payload).
uint64_t arena_alloc(void* handle, uint64_t size) {
  auto* h = static_cast<Handle*>(handle);
  if (!h || size == 0) return 0;
  size = (size + kAlign - 1) & ~(kAlign - 1);
  ArenaHeader* hd = header(h);
  if (lock_arena(hd) != 0) return 0;

  uint64_t result = 0;
  for (Block* b = first_block(h); b != nullptr; b = next_block(h, b)) {
    if (b->free) {
      // Deferred coalescing: free-time merging only looks forward, so runs of
      // blocks freed in ascending address order stay split until this scan
      // stitches them back together.
      for (Block* n = next_block(h, b); n != nullptr && n->free; n = next_block(h, b)) {
        b->size += kBlockSize + n->size;
      }
    }
    if (!b->free || b->size < size) continue;
    uint64_t remainder = b->size - size;
    if (remainder > kBlockSize + kAlign) {
      // Split: carve the tail into a new free block. CRASH-CONSISTENT
      // ORDER (the robust mutex hands the table to a survivor if this
      // process dies mid-split): (1) write the tail header while it is
      // still invisible scribble inside b's payload, (2) shrink b — a walker
      // now sees two valid free blocks, (3) only then claim b below. Any
      // kill point leaves a walkable table; the old order (shrink first)
      // lost everything past the split until the tail header existed.
      auto* tail = reinterpret_cast<Block*>(
          reinterpret_cast<uint8_t*>(b) + kBlockSize + size);
      tail->size = remainder - kBlockSize;
      tail->free = 1;
      b->size = size;
    }
    b->free = 0;
    hd->used += b->size;
    if (hd->used > hd->high_water) hd->high_water = hd->used;
    result = static_cast<uint64_t>(
        reinterpret_cast<uint8_t*>(b) + kBlockSize - h->base);
    break;
  }
  pthread_mutex_unlock(&hd->lock);
  return result;
}

// Free the allocation whose payload starts at `offset`. Returns 0 on success.
int arena_free(void* handle, uint64_t offset) {
  auto* h = static_cast<Handle*>(handle);
  if (!h || offset < kHeaderSize + kBlockSize || offset >= h->map_size) return -EINVAL;
  auto* b = reinterpret_cast<Block*>(h->base + offset - kBlockSize);
  ArenaHeader* hd = header(h);
  if (lock_arena(hd) != 0) return -EAGAIN;
  if (b->free) { pthread_mutex_unlock(&hd->lock); return -EINVAL; }
  b->free = 1;
  hd->used -= b->size;
  // Coalesce with following free blocks (address-ordered walk from b).
  for (Block* n = next_block(h, b); n != nullptr && n->free; n = next_block(h, b)) {
    b->size += kBlockSize + n->size;
  }
  pthread_mutex_unlock(&hd->lock);
  return 0;
}

uint64_t arena_used(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h ? header(h)->used : 0;
}

uint64_t arena_capacity(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h ? header(h)->capacity : 0;
}

uint64_t arena_high_water(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h ? header(h)->high_water : 0;
}

// Base pointer for zero-copy views (ctypes turns this into a memoryview).
void* arena_base(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h ? h->base : nullptr;
}

uint64_t arena_map_size(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h ? h->map_size : 0;
}

// Hash of the source this binary was built from (stale-binary guard).
const char* arena_source_hash(void) { return ARENA_SRC_SHA256; }

}  // extern "C"
