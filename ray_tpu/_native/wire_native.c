/* wire_native: compact tagged binary codec for ray_tpu control messages.
 *
 * The control plane's per-message cost is dominated by pickling small,
 * fixed-shape tuples (submit/exec/done/batch/ref-op frames): the C pickler
 * pays generic machinery (memo table, framing, protocol opcodes) that a
 * purpose-built codec does not need. This module encodes the closed set of
 * "simple" Python values (None, bool, int64, float, bytes, str, tuple,
 * list, dict) directly, and escapes to Python-level hooks for everything
 * else — the hooks flatten the runtime's dataclasses (TaskSpec, ObjectMeta,
 * ExecRequest, ids, ...) to simple field tuples and pickle anything truly
 * arbitrary (see ray_tpu/_private/wire.py, which also implements the SAME
 * format in pure Python as the no-toolchain fallback and the parity-fuzz
 * reference).
 *
 * Format (little-endian):
 *   'N'            None            'T'/'F'  True/False
 *   'i' + i64      int             'f' + f64  float
 *   'b' + u32 + data   bytes       's' + u32 + utf8   str
 *   't'/'l' + u32 + items          tuple / list
 *   'd' + u32 + key,value pairs    dict (insertion order preserved)
 *   'H' + u8 tag + payload         hook-encoded object
 *
 * Errors raise ValueError; callers fall back to pickle for the whole
 * message, so an unencodable value costs the attempt, never correctness.
 *
 * Built with the same on-demand g++ flow as shm_arena (ray_tpu/_native/
 * __init__.py); no toolchain => the pure-Python codec serves.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

#define WIRE_MAX_DEPTH 100

/* Source-hash stamp: the build flow (_native/__init__.py) passes
 * -DWIRE_SRC_SHA256="<hex>" with the sha256 of THIS file, exported both as a
 * module constant (SOURCE_HASH) and as a greppable marker string inside the
 * binary, so a checked-in .so that no longer matches its source is
 * detectable without loading it (tools/check.sh stale-binary guard). */
#ifndef WIRE_SRC_SHA256
#define WIRE_SRC_SHA256 "unknown"
#endif
__attribute__((used)) static const char wire_src_marker[] =
    "RAY_TPU_WIRE_SRC_SHA256=" WIRE_SRC_SHA256;

static PyObject *enc_hook = NULL; /* obj -> (tag:int 0..255, payload) | None */
static PyObject *dec_hook = NULL; /* (tag, payload) -> obj */

/* Decode-side frame ceiling (config knob wire_max_frame_bytes, pushed in by
 * wire.py via set_limits). A frame larger than this is rejected up front —
 * no interior length field of a hostile frame is ever trusted into an
 * allocation bigger than the frame itself (see the count checks below). */
static Py_ssize_t max_frame_bytes = 256 * 1024 * 1024;

/* ------------------------------------------------------------------ writer */
typedef struct {
    char *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} Writer;

static int w_init(Writer *w, Py_ssize_t cap) {
    w->buf = (char *)PyMem_Malloc(cap);
    if (!w->buf) {
        PyErr_NoMemory();
        return -1;
    }
    w->len = 0;
    w->cap = cap;
    return 0;
}

static int w_reserve(Writer *w, Py_ssize_t extra) {
    if (w->len + extra <= w->cap)
        return 0;
    Py_ssize_t cap = w->cap * 2;
    while (cap < w->len + extra)
        cap *= 2;
    char *nb = (char *)PyMem_Realloc(w->buf, cap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    w->buf = nb;
    w->cap = cap;
    return 0;
}

static inline int w_byte(Writer *w, char c) {
    if (w_reserve(w, 1) < 0)
        return -1;
    w->buf[w->len++] = c;
    return 0;
}

static inline int w_raw(Writer *w, const void *p, Py_ssize_t n) {
    if (w_reserve(w, n) < 0)
        return -1;
    memcpy(w->buf + w->len, p, n);
    w->len += n;
    return 0;
}

static inline int w_u32(Writer *w, Py_ssize_t v) {
    if (v < 0 || v > 0xFFFFFFFFLL) {
        PyErr_SetString(PyExc_ValueError, "wire: length exceeds u32");
        return -1;
    }
    uint32_t u = (uint32_t)v;
    return w_raw(w, &u, 4);
}

/* ----------------------------------------------------------------- encoder */
static int encode_obj(Writer *w, PyObject *o, int depth);

static int encode_via_hook(Writer *w, PyObject *o, int depth) {
    if (!enc_hook) {
        PyErr_SetString(PyExc_ValueError, "wire: no encode hook installed");
        return -1;
    }
    PyObject *r = PyObject_CallFunctionObjArgs(enc_hook, o, NULL);
    if (!r)
        return -1;
    if (r == Py_None) {
        Py_DECREF(r);
        PyErr_SetString(PyExc_ValueError, "wire: hook declined object");
        return -1;
    }
    if (!PyTuple_CheckExact(r) || PyTuple_GET_SIZE(r) != 2) {
        Py_DECREF(r);
        PyErr_SetString(PyExc_ValueError, "wire: hook must return (tag, payload)");
        return -1;
    }
    long tag = PyLong_AsLong(PyTuple_GET_ITEM(r, 0));
    if (tag < 0 || tag > 255) {
        Py_DECREF(r);
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "wire: hook tag out of range");
        return -1;
    }
    if (w_byte(w, 'H') < 0 || w_byte(w, (char)(unsigned char)tag) < 0) {
        Py_DECREF(r);
        return -1;
    }
    int rc = encode_obj(w, PyTuple_GET_ITEM(r, 1), depth + 1);
    Py_DECREF(r);
    return rc;
}

static int encode_obj(Writer *w, PyObject *o, int depth) {
    if (depth > WIRE_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire: max depth exceeded");
        return -1;
    }
    if (o == Py_None)
        return w_byte(w, 'N');
    if (o == Py_True)
        return w_byte(w, 'T');
    if (o == Py_False)
        return w_byte(w, 'F');
    PyTypeObject *t = Py_TYPE(o);
    if (t == &PyLong_Type) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(o, &overflow);
        if (overflow)
            return encode_via_hook(w, o, depth); /* big ints: pickle leaf */
        if (v == -1 && PyErr_Occurred())
            return -1;
        if (w_byte(w, 'i') < 0)
            return -1;
        int64_t iv = (int64_t)v;
        return w_raw(w, &iv, 8);
    }
    if (t == &PyFloat_Type) {
        double d = PyFloat_AS_DOUBLE(o);
        if (w_byte(w, 'f') < 0)
            return -1;
        return w_raw(w, &d, 8);
    }
    if (t == &PyBytes_Type) {
        Py_ssize_t n = PyBytes_GET_SIZE(o);
        if (w_byte(w, 'b') < 0 || w_u32(w, n) < 0)
            return -1;
        return w_raw(w, PyBytes_AS_STRING(o), n);
    }
    if (t == &PyUnicode_Type) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(o, &n);
        if (!s)
            return -1;
        if (w_byte(w, 's') < 0 || w_u32(w, n) < 0)
            return -1;
        return w_raw(w, s, n);
    }
    if (t == &PyTuple_Type) {
        Py_ssize_t n = PyTuple_GET_SIZE(o);
        if (w_byte(w, 't') < 0 || w_u32(w, n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++)
            if (encode_obj(w, PyTuple_GET_ITEM(o, i), depth + 1) < 0)
                return -1;
        return 0;
    }
    if (t == &PyList_Type) {
        Py_ssize_t n = PyList_GET_SIZE(o);
        if (w_byte(w, 'l') < 0 || w_u32(w, n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++)
            if (encode_obj(w, PyList_GET_ITEM(o, i), depth + 1) < 0)
                return -1;
        return 0;
    }
    if (t == &PyDict_Type) {
        Py_ssize_t n = PyDict_GET_SIZE(o);
        if (w_byte(w, 'd') < 0 || w_u32(w, n) < 0)
            return -1;
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        while (PyDict_Next(o, &pos, &k, &v)) {
            if (encode_obj(w, k, depth + 1) < 0 || encode_obj(w, v, depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    return encode_via_hook(w, o, depth);
}

/* ----------------------------------------------------------------- decoder */
typedef struct {
    const char *p;
    const char *end;
} Reader;

static int r_need(Reader *r, Py_ssize_t n) {
    if (r->end - r->p < n) {
        PyErr_SetString(PyExc_ValueError, "wire: truncated frame");
        return -1;
    }
    return 0;
}

static PyObject *decode_obj(Reader *r, int depth);

static int r_u32(Reader *r, uint32_t *out) {
    if (r_need(r, 4) < 0)
        return -1;
    memcpy(out, r->p, 4);
    r->p += 4;
    return 0;
}

static PyObject *decode_obj(Reader *r, int depth) {
    if (depth > WIRE_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire: max depth exceeded");
        return NULL;
    }
    if (r_need(r, 1) < 0)
        return NULL;
    char tag = *r->p++;
    switch (tag) {
    case 'N':
        Py_RETURN_NONE;
    case 'T':
        Py_RETURN_TRUE;
    case 'F':
        Py_RETURN_FALSE;
    case 'i': {
        if (r_need(r, 8) < 0)
            return NULL;
        int64_t v;
        memcpy(&v, r->p, 8);
        r->p += 8;
        return PyLong_FromLongLong((long long)v);
    }
    case 'f': {
        if (r_need(r, 8) < 0)
            return NULL;
        double d;
        memcpy(&d, r->p, 8);
        r->p += 8;
        return PyFloat_FromDouble(d);
    }
    case 'b': {
        uint32_t n;
        if (r_u32(r, &n) < 0 || r_need(r, n) < 0)
            return NULL;
        PyObject *o = PyBytes_FromStringAndSize(r->p, n);
        r->p += n;
        return o;
    }
    case 's': {
        uint32_t n;
        if (r_u32(r, &n) < 0 || r_need(r, n) < 0)
            return NULL;
        PyObject *o = PyUnicode_DecodeUTF8(r->p, n, NULL);
        r->p += n;
        return o;
    }
    case 't': {
        uint32_t n;
        if (r_u32(r, &n) < 0)
            return NULL;
        /* Each element costs at least 1 byte: a count beyond the remaining
         * input is a lie — reject BEFORE presizing (a 5-byte frame claiming
         * 2^32-1 elements must not allocate a 34GB container). */
        if ((Py_ssize_t)n > r->end - r->p) {
            PyErr_SetString(PyExc_ValueError, "wire: truncated frame");
            return NULL;
        }
        PyObject *tup = PyTuple_New(n);
        if (!tup)
            return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *item = decode_obj(r, depth + 1);
            if (!item) {
                Py_DECREF(tup);
                return NULL;
            }
            PyTuple_SET_ITEM(tup, i, item);
        }
        return tup;
    }
    case 'l': {
        uint32_t n;
        if (r_u32(r, &n) < 0)
            return NULL;
        if ((Py_ssize_t)n > r->end - r->p) {
            PyErr_SetString(PyExc_ValueError, "wire: truncated frame");
            return NULL;
        }
        PyObject *lst = PyList_New(n);
        if (!lst)
            return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *item = decode_obj(r, depth + 1);
            if (!item) {
                Py_DECREF(lst);
                return NULL;
            }
            PyList_SET_ITEM(lst, i, item);
        }
        return lst;
    }
    case 'd': {
        uint32_t n;
        if (r_u32(r, &n) < 0)
            return NULL;
        /* A pair costs at least 2 bytes; unlike PyList_New's lazy pages,
         * the presized dict table is TOUCHED, so this bound matters. */
        if ((Py_ssize_t)n > (r->end - r->p) / 2) {
            PyErr_SetString(PyExc_ValueError, "wire: truncated frame");
            return NULL;
        }
        PyObject *dct = _PyDict_NewPresized(n);
        if (!dct)
            return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *k = decode_obj(r, depth + 1);
            if (!k) {
                Py_DECREF(dct);
                return NULL;
            }
            PyObject *v = decode_obj(r, depth + 1);
            if (!v) {
                Py_DECREF(k);
                Py_DECREF(dct);
                return NULL;
            }
            if (PyDict_SetItem(dct, k, v) < 0) {
                Py_DECREF(k);
                Py_DECREF(v);
                Py_DECREF(dct);
                /* Unhashable key: the encoder never emits container keys,
                 * so this is a forged/corrupt frame — typed rejection
                 * (fuzzer-found; keep in sync with the Python twin). */
                if (PyErr_ExceptionMatches(PyExc_TypeError)) {
                    PyErr_Clear();
                    PyErr_SetString(PyExc_ValueError,
                                    "wire: unhashable dict key in frame");
                }
                return NULL;
            }
            Py_DECREF(k);
            Py_DECREF(v);
        }
        return dct;
    }
    case 'H': {
        if (r_need(r, 1) < 0)
            return NULL;
        unsigned char htag = (unsigned char)*r->p++;
        PyObject *payload = decode_obj(r, depth + 1);
        if (!payload)
            return NULL;
        if (!dec_hook) {
            Py_DECREF(payload);
            PyErr_SetString(PyExc_ValueError, "wire: no decode hook installed");
            return NULL;
        }
        PyObject *tagobj = PyLong_FromLong((long)htag);
        if (!tagobj) {
            Py_DECREF(payload);
            return NULL;
        }
        PyObject *out = PyObject_CallFunctionObjArgs(dec_hook, tagobj, payload, NULL);
        Py_DECREF(tagobj);
        Py_DECREF(payload);
        return out;
    }
    default:
        PyErr_Format(PyExc_ValueError, "wire: unknown type byte 0x%02x",
                     (unsigned char)tag);
        return NULL;
    }
}

/* ------------------------------------------------------------- module API */
static PyObject *py_pack(PyObject *self, PyObject *arg) {
    Writer w;
    if (w_init(&w, 256) < 0)
        return NULL;
    if (encode_obj(&w, arg, 0) < 0) {
        PyMem_Free(w.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

static PyObject *py_unpack(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t offset = 0;
    if (!PyArg_ParseTuple(args, "y*|n", &view, &offset))
        return NULL;
    if (offset < 0 || offset > view.len) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "wire: bad offset");
        return NULL;
    }
    if (view.len - offset > max_frame_bytes) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError,
                        "wire: frame exceeds wire_max_frame_bytes");
        return NULL;
    }
    Reader r = {(const char *)view.buf + offset,
                (const char *)view.buf + view.len};
    PyObject *out = decode_obj(&r, 0);
    if (out && r.p != r.end) {
        Py_DECREF(out);
        out = NULL;
        PyErr_SetString(PyExc_ValueError, "wire: trailing bytes in frame");
    }
    PyBuffer_Release(&view);
    return out;
}

static PyObject *py_set_hooks(PyObject *self, PyObject *args) {
    PyObject *enc, *dec;
    if (!PyArg_ParseTuple(args, "OO", &enc, &dec))
        return NULL;
    Py_XINCREF(enc);
    Py_XINCREF(dec);
    Py_XSETREF(enc_hook, enc == Py_None ? NULL : enc);
    Py_XSETREF(dec_hook, dec == Py_None ? NULL : dec);
    if (enc == Py_None)
        Py_XDECREF(enc); /* balanced: we incref'd but stored NULL */
    if (dec == Py_None)
        Py_XDECREF(dec);
    Py_RETURN_NONE;
}

static PyObject *py_set_limits(PyObject *self, PyObject *args) {
    Py_ssize_t max_frame;
    if (!PyArg_ParseTuple(args, "n", &max_frame))
        return NULL;
    if (max_frame <= 0) {
        PyErr_SetString(PyExc_ValueError, "wire: max_frame_bytes must be > 0");
        return NULL;
    }
    max_frame_bytes = max_frame;
    Py_RETURN_NONE;
}

static PyMethodDef wire_methods[] = {
    {"pack", py_pack, METH_O,
     "pack(obj) -> bytes — encode a simple-value structure (hooks for the rest)."},
    {"unpack", py_unpack, METH_VARARGS,
     "unpack(data[, offset]) -> obj — decode a frame produced by pack()."},
    {"set_hooks", py_set_hooks, METH_VARARGS,
     "set_hooks(encode_cb, decode_cb) — install the dataclass/pickle escape hooks."},
    {"set_limits", py_set_limits, METH_VARARGS,
     "set_limits(max_frame_bytes) — decode-side frame-size ceiling."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef wire_module = {
    PyModuleDef_HEAD_INIT, "wire_native",
    "Compact tagged wire codec for ray_tpu control messages.", -1, wire_methods,
};

PyMODINIT_FUNC PyInit_wire_native(void) {
    PyObject *mod = PyModule_Create(&wire_module);
    if (!mod)
        return NULL;
    /* Stale-binary guard: the hash of the source this .so was built from. */
    if (PyModule_AddStringConstant(mod, "SOURCE_HASH", WIRE_SRC_SHA256) < 0) {
        Py_DECREF(mod);
        return NULL;
    }
    return mod;
}
