"""`python -m ray_tpu <command>` — the CLI entry point."""

from ray_tpu.scripts.cli import main

if __name__ == "__main__":
    main()
