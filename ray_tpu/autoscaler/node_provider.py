"""NodeProvider plugin interface + built-in providers.

Reference: `python/ray/autoscaler/node_provider.py` (the plugin API cloud
providers implement) and `_private/fake_multi_node/node_provider.py:237`
(`FakeMultiNodeProvider`, the test double nearly every autoscaler test uses).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Create/terminate nodes of a named node type. `node_config` is the
    type's config dict (resources, labels, provider-specific fields)."""

    def create_node(self, node_type: str, node_config: Dict[str, Any]) -> str:
        """Launch one node; returns a provider node id."""
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Registers virtual nodes with the in-process scheduler — pure-logic
    autoscaler tests without processes (the fake_multi_node analogue)."""

    def __init__(self):
        self._nodes: Dict[str, Any] = {}

    def create_node(self, node_type: str, node_config: Dict[str, Any]) -> str:
        from ray_tpu._private.ids import NodeID
        from ray_tpu._private.worker import global_worker

        resources = dict(node_config.get("resources") or {})
        labels = {"autoscaler_node_type": node_type, **(node_config.get("labels") or {})}
        scheduler = global_worker.context.scheduler
        node_id: NodeID = scheduler.call("add_node", (resources, labels)).result()
        self._nodes[node_id.hex()] = node_id
        return node_id.hex()

    def terminate_node(self, provider_node_id: str) -> None:
        from ray_tpu._private.worker import global_worker

        node_id = self._nodes.pop(provider_node_id, None)
        if node_id is not None:
            global_worker.context.scheduler.call("remove_node", node_id).result()

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)


class LocalDaemonProvider(NodeProvider):
    """Spawns real node-daemon processes on this machine (the autoscaler
    variant of `cluster_utils.Cluster(real=True).add_node`)."""

    def __init__(self, head_address: str, authkey_hex: Optional[str] = None):
        self.head_address = head_address
        self.authkey_hex = authkey_hex or os.environ.get("RAY_TPU_AUTHKEY_HEX", "")
        self._procs: Dict[str, subprocess.Popen] = {}

    def create_node(self, node_type: str, node_config: Dict[str, Any]) -> str:
        from ray_tpu._private.launch import spawn_node_daemon

        shm_dir = tempfile.mkdtemp(prefix="ray_tpu_asnode_")
        labels = {"autoscaler_node_type": node_type, **(node_config.get("labels") or {})}
        proc, node_id = spawn_node_daemon(
            self.head_address,
            shm_dir=shm_dir,
            resources=node_config.get("resources") or {},
            labels=labels,
            authkey_hex=self.authkey_hex,
        )
        self._procs[node_id] = proc
        return node_id

    def terminate_node(self, provider_node_id: str) -> None:
        proc = self._procs.pop(provider_node_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [nid for nid, p in self._procs.items() if p.poll() is None]


class TpuQueuedResourcesProvider(NodeProvider):
    """GCP TPU queued-resources provider: each node type maps to a TPU pod
    slice requested via `gcloud compute tpus queued-resources create` (the
    GKE/queued-resources provider SURVEY §7 step 6 specifies; no reference
    equivalent — its providers are GPU-cloud only).

    Command construction is pure (unit-testable offline); execution requires
    gcloud credentials at runtime. Started slices join the cluster by running
    `python -m ray_tpu start --address ...` in their startup script.
    """

    def __init__(self, project: str, zone: str, head_address: str,
                 runner=subprocess.run):
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self._runner = runner
        self._requests: Dict[str, str] = {}  # request id -> node_type

    def _create_command(self, request_id: str, node_config: Dict[str, Any]) -> List[str]:
        accel = node_config["accelerator_type"]  # e.g. "v4-32"
        runtime = node_config.get("runtime_version", "tpu-ubuntu2204-base")
        startup = node_config.get(
            "startup_script",
            f"python -m ray_tpu start --address {self.head_address}",
        )
        return [
            "gcloud", "compute", "tpus", "queued-resources", "create", request_id,
            f"--project={self.project}",
            f"--zone={self.zone}",
            f"--node-id={request_id}",
            f"--accelerator-type={accel}",
            f"--runtime-version={runtime}",
            f"--metadata=startup-script={startup}",
        ]

    def _delete_command(self, request_id: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "queued-resources", "delete", request_id,
            f"--project={self.project}", f"--zone={self.zone}", "--quiet", "--force",
        ]

    def create_node(self, node_type: str, node_config: Dict[str, Any]) -> str:
        request_id = f"raytpu-{node_type}-{int(time.time())}"
        cmd = self._create_command(request_id, node_config)
        proc = self._runner(cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f"queued-resources create failed: {proc.stdout}")
        self._requests[request_id] = node_type
        return request_id

    def terminate_node(self, provider_node_id: str) -> None:
        self._requests.pop(provider_node_id, None)
        self._runner(
            self._delete_command(provider_node_id),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    def non_terminated_nodes(self) -> List[str]:
        return list(self._requests)
