"""Autoscaler: demand-driven node scale-up and idle scale-down.

Reference: `python/ray/autoscaler/` (~21k LoC — `StandardAutoscaler`
(`_private/autoscaler.py:172`), `Monitor` (`monitor.py:127`), cloud
`NodeProvider` plugins, `fake_multi_node` test provider). Same architecture,
TPU-first providers:

 - `StandardAutoscaler`: reads the scheduler's demand snapshot (pending task
   resource shapes + unplaced PG bundles + per-node idle time), bin-packs
   demand onto configured node types, asks the provider for nodes, and
   terminates nodes idle past the timeout (respecting min_workers).
 - `NodeProvider` plugins: `FakeMultiNodeProvider` (virtual scheduler nodes,
   the `fake_multi_node` analogue), `LocalDaemonProvider` (real node-daemon
   processes on this machine), and `TpuQueuedResourcesProvider` (gcloud
   queued-resources command builder for TPU pod slices — the provider SURVEY
   §7 step 6 calls for; requires gcloud at runtime).
 - `Monitor`: background thread driving the loop (the reference's monitor
   process, colocated here).
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    Monitor,
    NodeTypeConfig,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider,
    LocalDaemonProvider,
    NodeProvider,
    TpuQueuedResourcesProvider,
)
from ray_tpu.autoscaler.sdk import request_resources

__all__ = [
    "AutoscalerConfig",
    "NodeTypeConfig",
    "StandardAutoscaler",
    "Monitor",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "LocalDaemonProvider",
    "TpuQueuedResourcesProvider",
    "request_resources",
]
