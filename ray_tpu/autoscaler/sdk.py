"""Autoscaler SDK: explicit resource requests.

Reference: `python/ray/autoscaler/sdk.py` `request_resources` — set a demand
floor the autoscaler satisfies even with no pending tasks (pre-warming).
Applies to the process's active Monitor (set by `Monitor.start`)."""

from __future__ import annotations

from typing import Dict, List, Optional

_active_monitor = None


def _set_active_monitor(monitor) -> None:
    global _active_monitor
    _active_monitor = monitor


def request_resources(bundles: Optional[List[Dict[str, float]]] = None) -> None:
    if _active_monitor is None:
        raise RuntimeError("no autoscaler Monitor is running in this process")
    _active_monitor.autoscaler.request_resources(bundles or [])
