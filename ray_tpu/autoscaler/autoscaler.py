"""StandardAutoscaler + Monitor.

Reference: `python/ray/autoscaler/_private/autoscaler.py:172`
(`StandardAutoscaler.update`: read load metrics -> bin-pack pending demand
onto node types -> launch/terminate via the provider) and
`_private/monitor.py:127` (the loop). Same decomposition; the load source is
the scheduler's `autoscaler_state` snapshot instead of GCS load metrics.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    max_workers: int = 10
    min_workers: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    # Provider-specific extras (e.g. accelerator_type for queued resources).
    extra: Dict[str, Any] = field(default_factory=dict)

    def node_config(self) -> Dict[str, Any]:
        return {"resources": dict(self.resources), "labels": dict(self.labels), **self.extra}


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    # Max new nodes per update pass (the reference's upscaling_speed throttle).
    max_launches_per_update: int = 5


def _fits(capacity: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _consume(capacity: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        capacity[k] = capacity.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider):
        self.config = config
        self.provider = provider
        # provider node id -> node type
        self.launched: Dict[str, str] = {}
        self._explicit_demand: List[Dict[str, float]] = []

    # ------------------------------------------------------------------ sdk
    def request_resources(self, bundles: List[Dict[str, float]]) -> None:
        """Explicit demand floor (reference: `autoscaler.sdk.request_resources`)."""
        self._explicit_demand = [dict(b) for b in bundles]

    # ---------------------------------------------------------------- update
    def update(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """One reconcile pass over a scheduler `autoscaler_state` snapshot.
        Returns {"launched": [(type, id)], "terminated": [id]}."""
        launched, terminated = [], []

        # 1) Unmet demand: pending shapes that fit on no node's AVAILABLE
        #    capacity right now (scratch-consumed so N identical pending tasks
        #    need N slots, not one).
        scratch = [dict(n["available"]) for n in state["nodes"] if n["alive"]]
        unmet: List[Dict[str, float]] = []
        demands = (
            list(state["pending_tasks"])
            + list(state["pending_bundles"])
            + list(self._explicit_demand)
        )
        for d in demands:
            if not d:
                continue
            placed = False
            for cap in scratch:
                if _fits(cap, d):
                    _consume(cap, d)
                    placed = True
                    break
            if not placed:
                unmet.append(d)

        # 2) Bin-pack unmet demand onto launchable node types.
        counts = self._count_by_type()
        to_launch: List[str] = []
        for d in unmet:
            if len(to_launch) >= self.config.max_launches_per_update:
                break
            for name, nt in self.config.node_types.items():
                pending_of_type = counts.get(name, 0) + sum(1 for t in to_launch if t == name)
                if pending_of_type >= nt.max_workers:
                    continue
                if _fits(dict(nt.resources), d):
                    to_launch.append(name)
                    break
        # min_workers floor.
        for name, nt in self.config.node_types.items():
            have = counts.get(name, 0) + sum(1 for t in to_launch if t == name)
            for _ in range(max(0, nt.min_workers - have)):
                to_launch.append(name)

        for name in to_launch:
            nid = self.provider.create_node(name, self.config.node_types[name].node_config())
            self.launched[nid] = name
            launched.append((name, nid))

        # 3) Scale down: autoscaler-launched nodes idle past the timeout
        #    (never below min_workers, never nodes hosting actors).
        by_id = {n["node_id"]: n for n in state["nodes"]}
        counts = self._count_by_type()
        for nid, ntype in list(self.launched.items()):
            info = by_id.get(nid)
            if info is None:
                continue  # not registered yet (or already gone)
            nt = self.config.node_types[ntype]
            if counts.get(ntype, 0) <= nt.min_workers:
                continue
            if info["actors"] > 0 or info["busy_workers"] > 0:
                continue
            if info["idle_s"] < self.config.idle_timeout_s:
                continue
            self.provider.terminate_node(nid)
            del self.launched[nid]
            counts[ntype] -= 1
            terminated.append(nid)

        # Scale decisions land in the cluster event log (no-op with metrics
        # off; never raises — a full event ring must not stall scaling).
        if launched:
            from ray_tpu._private.events import emit_event

            emit_event(
                "autoscaler_scale_up",
                f"autoscaler launched {len(launched)} node(s): "
                + ", ".join(f"{t}:{nid[:8]}" for t, nid in launched),
                source="autoscaler", launched=[t for t, _ in launched],
                unmet_demands=len(unmet),
            )
        if terminated:
            from ray_tpu._private.events import emit_event

            emit_event(
                "autoscaler_scale_down",
                f"autoscaler terminated {len(terminated)} idle node(s)",
                source="autoscaler",
                terminated=[nid[:8] for nid in terminated],
            )
        return {"launched": launched, "terminated": terminated}

    def _count_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for ntype in self.launched.values():
            counts[ntype] = counts.get(ntype, 0) + 1
        return counts


class Monitor:
    """Background loop driving StandardAutoscaler off live scheduler state
    (the reference's monitor process, colocated in the driver)."""

    def __init__(self, config: AutoscalerConfig, provider, interval_s: float = 1.0):
        self.autoscaler = StandardAutoscaler(config, provider)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        from ray_tpu.autoscaler.sdk import _set_active_monitor

        _set_active_monitor(self)
        self._thread = threading.Thread(target=self._loop, daemon=True, name="autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        from ray_tpu._private.worker import global_worker

        while not self._stop.wait(self.interval_s):
            try:
                state = global_worker.context.autoscaler_state()
                self.autoscaler.update(state)
            except Exception:
                pass  # cluster shutting down / transient; next tick retries

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
