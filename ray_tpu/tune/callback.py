"""Tune Callback API: experiment-lifecycle hooks.

Reference: `python/ray/tune/callback.py` (`Callback` — on_trial_start /
on_trial_result / on_trial_complete / on_trial_error / on_checkpoint /
on_experiment_end, invoked by the TrialRunner event loop) wired through
`RunConfig(callbacks=[...])`.

Hooks run in the DRIVER's event loop between scheduling decisions — keep
them cheap (a slow callback stalls every trial's next dispatch, exactly as
in the reference). Exceptions propagate and abort the experiment.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class Callback:
    """Base class; subclass and override the hooks you need."""

    def setup(self, **info) -> None:
        """Once, before the experiment's first trial launches."""

    def on_trial_start(self, iteration: int, trials: List, trial, **info) -> None:
        """A trial's actor was launched (also after RESTART relaunches)."""

    def on_trial_result(self, iteration: int, trials: List, trial,
                        result: Dict[str, Any], **info) -> None:
        """A trial reported metrics (before the scheduler's decision)."""

    def on_checkpoint(self, iteration: int, trials: List, trial,
                      checkpoint, **info) -> None:
        """A trial report carried a checkpoint (after registration)."""

    def on_trial_complete(self, iteration: int, trials: List, trial, **info) -> None:
        """A trial finished or was scheduler-stopped (not errored)."""

    def on_trial_error(self, iteration: int, trials: List, trial, **info) -> None:
        """A trial errored (actor death or user exception)."""

    def on_experiment_end(self, trials: List, **info) -> None:
        """The event loop drained: every trial is terminal."""


class CallbackList:
    """Fan-out helper the TrialRunner drives."""

    def __init__(self, callbacks: Optional[List[Callback]]):
        self._callbacks = list(callbacks or [])

    def __bool__(self) -> bool:
        return bool(self._callbacks)

    def __iter__(self):
        return iter(self._callbacks)

    def fire(self, hook: str, *args, **kwargs) -> None:
        for cb in self._callbacks:
            getattr(cb, hook)(*args, **kwargs)
