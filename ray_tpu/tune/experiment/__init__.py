from ray_tpu.tune.experiment.trial import Trial

__all__ = ["Trial"]
