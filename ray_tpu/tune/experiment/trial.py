"""Trial: one hyperparameter configuration's lifecycle record.

Reference: `python/ray/tune/experiment/trial.py` — status machine
(PENDING/RUNNING/PAUSED/TERMINATED/ERROR), per-trial directory, last result,
and checkpoint bookkeeping.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: Dict[str, Any], experiment_dir: str, index: int,
                 experiment_name: str = ""):
        self.trial_id = f"{uuid.uuid4().hex[:8]}"
        self.index = index
        self.config = config
        self.experiment_name = experiment_name
        self.name = f"trial_{index:04d}_{self.trial_id}"
        self.local_dir = os.path.join(experiment_dir, self.name)
        os.makedirs(self.local_dir, exist_ok=True)
        self.status = PENDING
        self.last_result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.num_results = 0
        self.restarts = 0
        self.checkpoint_manager = CheckpointManager(self.local_dir)
        # Set when (re)starting with a donor checkpoint (PBT exploit / resume).
        self.restore_checkpoint: Optional[Checkpoint] = None

    @property
    def checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint_manager.latest_checkpoint

    def metric(self, name: str, default: float = float("nan")) -> float:
        if not self.last_result:
            return default
        v = self.last_result.get(name, default)
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    def __repr__(self):
        return f"Trial({self.name}, {self.status}, results={self.num_results})"
