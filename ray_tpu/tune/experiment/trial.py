"""Trial: one hyperparameter configuration's lifecycle record.

Reference: `python/ray/tune/experiment/trial.py` — status machine
(PENDING/RUNNING/PAUSED/TERMINATED/ERROR), per-trial directory, last result,
and checkpoint bookkeeping.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: Dict[str, Any], experiment_dir: str, index: int,
                 experiment_name: str = "", trial_id: Optional[str] = None):
        self.trial_id = trial_id or f"{uuid.uuid4().hex[:8]}"
        self.index = index
        self.config = config
        self.experiment_name = experiment_name
        self.name = f"trial_{index:04d}_{self.trial_id}"
        self.local_dir = os.path.join(experiment_dir, self.name)
        os.makedirs(self.local_dir, exist_ok=True)
        self.status = PENDING
        self.last_result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.num_results = 0
        self.restarts = 0
        self.checkpoint_manager = CheckpointManager(self.local_dir)
        # Set when (re)starting with a donor checkpoint (PBT exploit / resume).
        self.restore_checkpoint: Optional[Checkpoint] = None

    # ------------------------------------------------------- journal (resume)
    def to_state(self) -> Dict[str, Any]:
        """JSON-serializable snapshot for the experiment journal (reference:
        `Trial.get_json_state`). Configs may hold arbitrary objects
        (functions, arrays), so the exact config rides as pickled hex; a
        scalar-filtered copy stays for human inspection."""
        from ray_tpu._private import serialization

        return {
            "trial_id": self.trial_id,
            "index": self.index,
            "config": {
                k: v for k, v in (self.config or {}).items()
                if isinstance(v, (int, float, str, bool))
            },
            "config_pkl": serialization.dumps(dict(self.config or {})).hex(),
            "status": self.status,
            "num_results": self.num_results,
            "last_result": {
                k: v for k, v in (self.last_result or {}).items()
                if isinstance(v, (int, float, str, bool))
            } or None,
            "error": self.error,
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any], experiment_dir: str,
                   experiment_name: str = "") -> "Trial":
        from ray_tpu._private import serialization

        if state.get("config_pkl"):
            config = serialization.loads(bytes.fromhex(state["config_pkl"]))
        else:
            config = dict(state.get("config") or {})
        t = cls(
            config,
            experiment_dir,
            int(state["index"]),
            experiment_name=experiment_name,
            trial_id=state["trial_id"],
        )
        t.status = state.get("status", PENDING)
        t.num_results = int(state.get("num_results", 0))
        t.last_result = state.get("last_result")
        t.error = state.get("error")
        t.checkpoint_manager.restore_from_disk()
        return t

    @property
    def checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint_manager.latest_checkpoint

    def metric(self, name: str, default: float = float("nan")) -> float:
        if not self.last_result:
            return default
        v = self.last_result.get(name, default)
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    def __repr__(self):
        return f"Trial({self.name}, {self.status}, results={self.num_results})"
