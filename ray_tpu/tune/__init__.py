"""Tune: distributed hyperparameter search over the ray_tpu runtime.

Reference: `python/ray/tune/` (P17 in SURVEY.md §2) — `Tuner`, the trial event
loop (`execution/trial_runner.py:1181`, `step():1358`), trial executor
(`execution/ray_trial_executor.py:185`), search spaces (`tune/search/`), and
schedulers (`tune/schedulers/`: ASHA, PBT, FIFO).

Architecture here: every trial runs its function trainable inside one actor
(reusing Train's thread-based session for report streaming), and the
`TrialRunner` multiplexes `next_result` futures across live trials with
`ray_tpu.wait` — the same actor-substrate design the reference uses, minus
the legacy class-Trainable RPC surface.
"""

from ray_tpu.tune.search.sample import (
    choice,
    grid_search,
    lograndint,
    loguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.callback import Callback
from ray_tpu.tune.stopper import (
    CombinedStopper,
    FunctionStopper,
    MaximumIterationStopper,
    Stopper,
    TrialPlateauStopper,
)
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.tune_config import TuneConfig
from ray_tpu.tune.tuner import Tuner, with_parameters
from ray_tpu.tune.experiment.trial import Trial

# `tune.report` parity alias: inside a function trainable, air session is live.
from ray_tpu.air.session import report, get_checkpoint

__all__ = [
    "Callback",
    "CombinedStopper",
    "FunctionStopper",
    "MaximumIterationStopper",
    "Stopper",
    "TrialPlateauStopper",
    "with_parameters",
    "ResultGrid",
    "Trial",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "lograndint",
    "loguniform",
    "qrandint",
    "quniform",
    "randint",
    "randn",
    "report",
    "sample_from",
    "uniform",
]
