"""TPESearcher: tree-structured Parzen estimator search, dependency-free.

The native model-based searcher of this build — the role Optuna/HyperOpt
wrappers play in the reference (`python/ray/tune/search/optuna/`,
`search/hyperopt/`; both default to TPE). Algorithm (Bergstra et al. 2011):
split observed trials at the gamma-quantile of the objective into good/bad
sets, model each set's density per dimension with a Parzen (Gaussian-kernel)
estimator, draw candidates from the good model l(x), and pick the candidate
maximizing l(x)/g(x).

Independent per-dimension models (like HyperOpt); Float/Integer dims use KDE
in (log-)value space, Categorical dims use smoothed category frequencies.
Function/Normal dims fall back to fresh random draws (no bounded support to
model)."""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.basic_variant import _find_axes, _set_path
from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _get_path(cfg: Dict, path: Tuple) -> Any:
    node = cfg
    for k in path:
        node = node[k]
    return node


class _NumericDim:
    """Parzen model over a bounded (possibly log, possibly quantized) dim."""

    def __init__(self, domain):
        self.domain = domain
        self.log = bool(domain.log)
        self.lo = math.log(domain.lower) if self.log else float(domain.lower)
        self.hi = math.log(domain.upper) if self.log else float(domain.upper)

    def to_unit(self, v: float) -> float:
        x = math.log(v) if self.log else float(v)
        return (x - self.lo) / max(self.hi - self.lo, 1e-12)

    def from_unit(self, u: float, rng: random.Random) -> Any:
        u = min(max(u, 0.0), 1.0)
        x = self.lo + u * (self.hi - self.lo)
        v = math.exp(x) if self.log else x
        d = self.domain
        if isinstance(d, Integer):
            v = int(round(v))
            if d.q:
                v = int(round(v / d.q) * d.q)
            return max(d.lower, min(v, d.upper - 1))
        if d.q:
            v = round(v / d.q) * d.q
        return min(max(v, d.lower), d.upper)

    @staticmethod
    def kde_sample(points: List[float], rng: random.Random) -> float:
        """Draw from the Parzen mixture over unit-scaled observations."""
        if not points:
            return rng.random()
        bw = max(1.0 / (1 + len(points)) ** 0.8, 1e-3)
        c = points[rng.randrange(len(points))]
        return rng.gauss(c, bw)

    @staticmethod
    def kde_logpdf(x: float, points: List[float]) -> float:
        """Log-density of the Parzen mixture (uniform prior when empty)."""
        if not points:
            return 0.0
        bw = max(1.0 / (1 + len(points)) ** 0.8, 1e-3)
        arr = np.asarray(points)
        z = (x - arr) / bw
        log_k = -0.5 * z * z - math.log(bw * math.sqrt(2 * math.pi))
        m = float(np.max(log_k))
        return m + math.log(float(np.exp(log_k - m).sum()) / len(points))


class TPESearcher(Searcher):
    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        n_initial_points: int = 10,
        gamma: float = 0.25,
        n_candidates: int = 24,
    ):
        super().__init__(metric, mode)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._observations: List[Tuple[Dict[str, Any], float]] = []
        self._configs: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------------ seam
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("set_search_properties was not called")
        if len(self._observations) < self.n_initial:
            cfg = self._random_config()
        else:
            cfg = self._tpe_config()
        self._configs[trial_id] = cfg
        return cfg

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict[str, Any]] = None,
        error: bool = False,
    ) -> None:
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or error or not result:
            return
        obj = self._objective(result)
        if obj is not None and math.isfinite(obj):
            self._observations.append((cfg, obj))

    # ------------------------------------------------------------------- TPE
    def _tpe_config(self) -> Dict[str, Any]:
        _, samples = _find_axes(self._space)
        obs = sorted(self._observations, key=lambda o: o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(obs))))
        good, bad = obs[:n_good], obs[n_good:]
        cfg = self._random_config()  # Function/Normal dims keep random draws
        for path, domain in samples:
            choice = self._suggest_dim(path, domain, good, bad)
            if choice is not None:
                _set_path(cfg, path, choice)
        return cfg

    def _suggest_dim(self, path, domain: Domain, good, bad):
        rng = self._rng
        if isinstance(domain, (Float, Integer)):
            dim = _NumericDim(domain)
            g_pts = [dim.to_unit(_get_path(c, path)) for c, _ in good]
            b_pts = [dim.to_unit(_get_path(c, path)) for c, _ in bad]
            best, best_score = None, -math.inf
            for _ in range(self.n_candidates):
                u = dim.kde_sample(g_pts, rng)
                score = dim.kde_logpdf(u, g_pts) - dim.kde_logpdf(u, b_pts)
                if score > best_score:
                    best, best_score = u, score
            return dim.from_unit(best, rng)
        if isinstance(domain, Categorical):
            cats = domain.categories

            def counts(obs_set):
                c = np.ones(len(cats))  # +1 smoothing
                for cfg, _ in obs_set:
                    v = _get_path(cfg, path)
                    try:
                        c[cats.index(v)] += 1
                    except ValueError:
                        pass
                return c / c.sum()

            pg, pb = counts(good), counts(bad)
            scores = np.log(pg) - np.log(pb)
            # Sample from the good distribution, keep the best-scoring of a few.
            cand = np.random.default_rng(rng.randrange(2**31)).choice(
                len(cats), size=min(self.n_candidates, 8), p=pg
            )
            best = max(cand, key=lambda i: scores[i])
            return cats[int(best)]
        return None  # unmodeled Domain kinds keep their random draw
