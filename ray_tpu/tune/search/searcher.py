"""Searcher: the adaptive search-algorithm seam of Tune.

Reference: `python/ray/tune/search/searcher.py` (`Searcher` —
`suggest(trial_id) -> config`, `on_trial_complete(trial_id, result)`), the
interface behind HyperOpt/Optuna/BayesOpt integrations. Unlike
BasicVariantGenerator (which expands all configs up front), a Searcher is
consulted as capacity frees, so later trials condition on earlier results.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.search.basic_variant import _find_axes, _materialize, _set_path
from ray_tpu.tune.search.sample import Function


class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode
        self._space: Optional[Dict[str, Any]] = None
        self._rng = random.Random(0)

    def set_search_properties(
        self, metric: Optional[str], mode: Optional[str], space: Dict[str, Any],
        seed: int = 0,
    ) -> None:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        self._space = space
        self._rng = random.Random(seed)
        grids, _ = _find_axes(space)
        if grids:
            raise ValueError(
                "grid_search axes are exhaustive, not adaptive — use "
                "BasicVariantGenerator (no search_alg) for grids"
            )

    # ------------------------------------------------------------- interface
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config to try (None = no more suggestions)."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        """Intermediate result (optional hook)."""

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict[str, Any]] = None,
        error: bool = False,
    ) -> None:
        """Terminal result for a suggested trial."""

    # --------------------------------------------------------------- helpers
    def _random_config(self) -> Dict[str, Any]:
        _, samples = _find_axes(self._space)
        cfg = _materialize(self._space) or {}
        for path, domain in samples:
            if isinstance(domain, Function):
                _set_path(cfg, path, domain.sample(self._rng, cfg))
            else:
                _set_path(cfg, path, domain.sample(self._rng))
        return cfg

    def _objective(self, result: Dict[str, Any]) -> Optional[float]:
        if not self.metric or self.metric not in result:
            return None
        v = float(result[self.metric])
        return -v if self.mode == "max" else v


class RandomSearcher(Searcher):
    """Independent random sampling through the adaptive seam (the baseline
    any model-based searcher must beat)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        return self._random_config()
