"""Search-space primitives: the vocabulary of `param_space`.

Reference: `python/ray/tune/search/sample.py` (`Domain`, `Float`, `Integer`,
`Categorical`, `Function`) and `tune/search/variant_generator.py`'s
`grid_search` marker. A Domain knows how to draw one value; grid_search marks
an axis for exhaustive expansion by `BasicVariantGenerator`.
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False, q: Optional[float] = None):
        if log and (lower <= 0 or upper <= 0):
            raise ValueError("loguniform requires positive bounds")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False, q: Optional[int] = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> int:
        if self.log:
            v = int(math.exp(rng.uniform(math.log(self.lower), math.log(self.upper))))
        else:
            v = rng.randint(self.lower, self.upper - 1)
        if self.q:
            v = int(round(v / self.q) * self.q)
        return max(self.lower, min(v, self.upper - 1))


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng: random.Random) -> float:
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable[[Dict[str, Any]], Any]):
        self.fn = fn

    def sample(self, rng: random.Random, spec: Optional[Dict[str, Any]] = None) -> Any:
        try:
            return self.fn(spec or {})
        except TypeError:
            return self.fn()


# ----------------------------------------------------------------- public API
def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}
