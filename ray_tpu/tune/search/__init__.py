from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import RandomSearcher, Searcher
from ray_tpu.tune.search.tpe import TPESearcher
from ray_tpu.tune.search.sample import (
    Categorical,
    Domain,
    Float,
    Function,
    Integer,
    choice,
    grid_search,
    lograndint,
    loguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)

__all__ = [
    "BasicVariantGenerator",
    "RandomSearcher",
    "Searcher",
    "TPESearcher",
    "Categorical",
    "Domain",
    "Float",
    "Function",
    "Integer",
    "choice",
    "grid_search",
    "lograndint",
    "loguniform",
    "qrandint",
    "quniform",
    "randint",
    "randn",
    "sample_from",
    "uniform",
]
