"""Variant generation: param_space -> concrete trial configs.

Reference: `python/ray/tune/search/basic_variant.py` (`BasicVariantGenerator`)
+ `variant_generator.py`: grid axes expand exhaustively (cartesian product,
recursing into nested dicts); Domain leaves are sampled per variant;
`num_samples` repeats the whole expansion with fresh samples.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Tuple

from ray_tpu.tune.search.sample import Domain, Function


def _find_axes(space: Any, path: Tuple = ()) -> Tuple[List, List]:
    """Walk the space: returns (grid_axes, sample_points) as (path, payload)."""
    grids, samples = [], []
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            grids.append((path, space["grid_search"]))
            return grids, samples
        for k, v in space.items():
            g, s = _find_axes(v, path + (k,))
            grids.extend(g)
            samples.extend(s)
    elif isinstance(space, Domain):
        samples.append((path, space))
    return grids, samples


def _set_path(cfg: Dict, path: Tuple, value: Any) -> None:
    node = cfg
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _materialize(space: Any) -> Dict:
    """Deep-copy the space with grid/Domain placeholders left as None."""
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            return None  # type: ignore[return-value]
        return {k: _materialize(v) for k, v in space.items()}
    if isinstance(space, Domain):
        return None  # type: ignore[return-value]
    return space


class BasicVariantGenerator:
    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def generate(self, space: Dict[str, Any], num_samples: int = 1) -> Iterator[Dict]:
        grids, samples = _find_axes(space)
        grid_values = [vals for _, vals in grids]
        for _ in range(max(num_samples, 1)):
            for combo in itertools.product(*grid_values) if grids else [()]:
                cfg = _materialize(space) or {}
                for (path, _), value in zip(grids, combo):
                    _set_path(cfg, path, value)
                for path, domain in samples:
                    if isinstance(domain, Function):
                        _set_path(cfg, path, domain.sample(self._rng, cfg))
                    else:
                        _set_path(cfg, path, domain.sample(self._rng))
                yield cfg

    def count(self, space: Dict[str, Any], num_samples: int = 1) -> int:
        grids, _ = _find_axes(space)
        n = max(num_samples, 1)
        for _, vals in grids:
            n *= len(vals)
        return n
