"""Stoppers: programmatic trial/experiment stopping conditions.

Reference: `python/ray/tune/stopper/` (`Stopper` ABC — `__call__(trial_id,
result) -> bool` stops one trial, `stop_all() -> bool` ends the experiment —
plus MaximumIterationStopper / TrialPlateauStopper / FunctionStopper),
accepted by `RunConfig(stop=...)` alongside the metric-threshold dict.
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Callable, Dict


class Stopper:
    """Interface: return True from __call__ to stop that trial; True from
    stop_all() to end the whole experiment after the current step."""

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class FunctionStopper(Stopper):
    """Adapts a plain `(trial_id, result) -> bool` callable."""

    def __init__(self, fn: Callable[[str, Dict[str, Any]], bool]):
        self._fn = fn

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        return bool(self._fn(trial_id, result))


class MaximumIterationStopper(Stopper):
    """Stop each trial after `max_iter` reported results (reference:
    `stopper/maximum_iteration.py`)."""

    def __init__(self, max_iter: int):
        self._max_iter = int(max_iter)

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        return result.get("training_iteration", 0) >= self._max_iter


class TrialPlateauStopper(Stopper):
    """Stop a trial whose `metric` stopped moving: the last `num_results`
    values' stddev fell below `std` after at least `grace_period` results
    (reference: `stopper/trial_plateau.py`)."""

    def __init__(self, metric: str, std: float = 0.01, num_results: int = 4,
                 grace_period: int = 4):
        self._metric = metric
        self._std = float(std)
        self._num_results = int(num_results)
        self._grace = int(grace_period)
        self._window: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=self._num_results)
        )
        self._count: Dict[str, int] = defaultdict(int)

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        if self._metric not in result:
            return False
        self._count[trial_id] += 1
        w = self._window[trial_id]
        w.append(float(result[self._metric]))
        if self._count[trial_id] < self._grace or len(w) < self._num_results:
            return False
        import numpy as np

        return float(np.std(w)) <= self._std


class CombinedStopper(Stopper):
    """OR over several stoppers (reference: `stopper/__init__.py`)."""

    def __init__(self, *stoppers: Stopper):
        self._stoppers = stoppers

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        return any(s(trial_id, result) for s in self._stoppers)

    def stop_all(self) -> bool:
        return any(s.stop_all() for s in self._stoppers)


def coerce_stopper(stop: Any):
    """RunConfig.stop accepts: None, a metric-threshold dict (handled by the
    TrialRunner directly), a Stopper, or a (trial_id, result) callable."""
    if stop is None or isinstance(stop, dict) or isinstance(stop, Stopper):
        return stop
    if callable(stop):
        return FunctionStopper(stop)
    raise TypeError(
        f"stop must be a dict, Stopper, or callable; got {type(stop)}"
    )
