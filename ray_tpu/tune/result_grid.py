"""ResultGrid: the indexed outcome of a Tuner run.

Reference: `python/ray/tune/result_grid.py` — per-trial `Result`s plus
`get_best_result(metric, mode)`.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.air.result import Result


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode or "max"
        if metric is None:
            raise ValueError("metric is required (set it here or in TuneConfig)")
        scored = [
            r for r in self._results
            if r.metrics is not None and metric in r.metrics
        ]
        if not scored:
            raise RuntimeError("no trial reported the requested metric")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])
