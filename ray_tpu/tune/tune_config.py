"""TuneConfig: search/schedule settings for a Tuner run.

Reference: `python/ray/tune/tune_config.py` — metric/mode, num_samples,
max_concurrent_trials, scheduler, and (here) per-trial resources.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: Optional[str] = None
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[Any] = None  # TrialScheduler
    # Adaptive search algorithm (Searcher, e.g. TPESearcher); None = the
    # up-front BasicVariantGenerator expansion.
    search_alg: Optional[Any] = None
    search_seed: int = 0
    resources_per_trial: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})

    def __post_init__(self):
        if self.mode is not None and self.mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        if self.num_samples < 1:
            raise ValueError("num_samples must be >= 1")
