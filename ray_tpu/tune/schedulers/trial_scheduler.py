"""Scheduler interface: per-report trial decisions.

Reference: `python/ray/tune/schedulers/trial_scheduler.py` — the runner asks
the scheduler after every result; CONTINUE keeps the trial running, STOP
terminates it (ASHA pruning), RESTART tears the actor down and relaunches
from `trial.restore_checkpoint` with (possibly mutated) `trial.config` (the
PBT exploit/explore path).
"""

from __future__ import annotations

from typing import Any, Dict

CONTINUE = "CONTINUE"
STOP = "STOP"
RESTART = "RESTART"


class TrialScheduler:
    CONTINUE = CONTINUE
    STOP = STOP
    RESTART = RESTART

    def on_trial_add(self, runner, trial) -> None:
        pass

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""
