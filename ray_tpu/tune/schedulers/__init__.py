from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.schedulers.async_hyperband import AsyncHyperBandScheduler
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "TrialScheduler",
]
