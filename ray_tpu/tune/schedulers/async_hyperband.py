"""ASHA: asynchronous successive halving.

Reference: `python/ray/tune/schedulers/async_hyperband.py`
(`AsyncHyperBandScheduler`): rungs at grace_period * reduction_factor^k; a
trial reaching a rung is stopped unless it is in the top 1/reduction_factor
of results recorded at that rung so far (asynchronous: judged against what
has been seen, never waiting for stragglers).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, STOP, TrialScheduler


class _Rung:
    def __init__(self, milestone: float):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}  # trial_id -> metric

    def cutoff(self, reduction_factor: float) -> float:
        """The score needed to be in the top 1/rf fraction (in max terms)."""
        vals = sorted(self.recorded.values())
        if not vals:
            return float("-inf")
        k = int(len(vals) * (1 - 1 / reduction_factor))
        return vals[min(k, len(vals) - 1)]


class AsyncHyperBandScheduler(TrialScheduler):
    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str = None,
        mode: str = None,
        max_t: float = 100,
        grace_period: float = 1,
        reduction_factor: float = 4,
    ):
        if grace_period <= 0 or reduction_factor <= 1 or max_t < grace_period:
            raise ValueError("invalid ASHA parameters")
        self._time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self._rf = reduction_factor
        rungs: List[_Rung] = []
        t = grace_period
        while t < max_t:
            rungs.append(_Rung(t))
            t *= reduction_factor
        # Judged from the largest milestone downward (reference behavior).
        self._rungs = list(reversed(rungs))

    def set_objective(self, metric: str, mode: str) -> None:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        if self.metric is None or self.mode is None:
            raise ValueError(
                "ASHA needs a metric and mode (set them on the scheduler or in "
                "TuneConfig)"
            )

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr)
        raw = result.get(self.metric)
        if t is None or raw is None:
            return CONTINUE
        value = float(raw) if self.mode == "max" else -float(raw)
        decision = CONTINUE
        for rung in self._rungs:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            cutoff = rung.cutoff(self._rf)
            rung.recorded[trial.trial_id] = value
            if value < cutoff and not math.isinf(cutoff):
                decision = STOP
            break  # only the highest newly-reached rung judges this result
        return decision
