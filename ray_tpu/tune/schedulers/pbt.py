"""Population Based Training.

Reference: `python/ray/tune/schedulers/pbt.py` (`PopulationBasedTraining`):
every `perturbation_interval` units of `time_attr`, trials in the bottom
quantile EXPLOIT a top-quantile trial (clone its latest checkpoint) and
EXPLORE its hyperparameters (resample or perturb by 1.2x / 0.8x). The runner
executes the decision by restarting the trial's actor from
`trial.restore_checkpoint` with the mutated `trial.config`.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Union

from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, RESTART, TrialScheduler
from ray_tpu.tune.search.sample import Domain


class PopulationBasedTraining(TrialScheduler):
    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str = None,
        mode: str = None,
        perturbation_interval: float = 10,
        hyperparam_mutations: Dict[str, Union[List, Domain, Callable]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: int = 0,
    ):
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations is required for PBT")
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self._time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}

    def set_objective(self, metric: str, mode: str) -> None:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        if self.metric is None or self.mode is None:
            raise ValueError(
                "PBT needs a metric and mode (set them on the scheduler or in "
                "TuneConfig)"
            )

    # ------------------------------------------------------------------ explore
    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self._mutations.items():
            if key not in new:
                continue
            if isinstance(spec, list):
                if self._rng.random() < self._resample_prob or new[key] not in spec:
                    new[key] = self._rng.choice(spec)
                else:  # shift to a neighboring value
                    i = spec.index(new[key])
                    new[key] = spec[max(0, min(len(spec) - 1, i + self._rng.choice([-1, 1])))]
            elif isinstance(spec, Domain):
                if self._rng.random() < self._resample_prob:
                    new[key] = spec.sample(self._rng)
                else:
                    new[key] = new[key] * self._rng.choice([0.8, 1.2])
            elif callable(spec):
                if self._rng.random() < self._resample_prob:
                    new[key] = spec()
                else:
                    new[key] = new[key] * self._rng.choice([0.8, 1.2])
        return new

    # ------------------------------------------------------------------- decide
    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr)
        if t is None or self.metric not in result:
            return CONTINUE
        last = self._last_perturb.get(trial.trial_id, 0.0)
        if t - last < self._interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t

        sign = 1.0 if self.mode == "max" else -1.0
        population = [
            tr for tr in runner.trials
            if tr.last_result and self.metric in tr.last_result
        ]
        if len(population) < 2:
            return CONTINUE
        ranked = sorted(
            population, key=lambda tr: sign * tr.metric(self.metric), reverse=True
        )
        k = max(1, int(len(ranked) * self._quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial not in bottom or trial in top:
            return CONTINUE
        donors = [tr for tr in top if tr.checkpoint is not None]
        if not donors:
            return CONTINUE
        donor = self._rng.choice(donors)
        trial.restore_checkpoint = donor.checkpoint
        trial.config = self._explore(donor.config)
        return RESTART
