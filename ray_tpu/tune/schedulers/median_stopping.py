"""MedianStoppingRule: stop trials whose running-average objective falls
below the median of prior trials' running averages at the same step.

Reference: `python/ray/tune/schedulers/median_stopping_rule.py` (Golovin et
al., "Google Vizier"). A trial is gated only after `grace_period` results and
once `min_samples_required` trials have reported at that step.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, STOP, TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        grace_period: int = 1,
        min_samples_required: int = 3,
        hard_stop: bool = True,
    ):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.hard_stop = hard_stop
        # trial_id -> list of objective values (sign-normalized: higher=better)
        self._history: Dict[str, List[float]] = {}

    def set_objective(self, metric, mode) -> None:
        # Constructor values win over TuneConfig's (same rule as ASHA/PBT).
        self.metric = self.metric or metric
        self.mode = self.mode or mode or "max"

    def _obj(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        if not self.metric or self.metric not in result:
            return CONTINUE
        hist = self._history.setdefault(trial.trial_id, [])
        hist.append(self._obj(result))
        step = len(hist)
        if step <= self.grace_period:
            return CONTINUE
        # Running averages of OTHER trials up to this step. Peers count with
        # WHATEVER history they have so far (truncated to `step`), matching
        # the reference rule's running-average-at-time-t: requiring peers to
        # have reached the same step let a trial that ran ahead of the pack
        # (uncontended worker while the rest were still spawning) escape
        # stopping entirely — every check saw too few same-step peers.
        peers = [
            float(np.mean(h[:step]))
            for tid, h in self._history.items()
            if tid != trial.trial_id and len(h) > 0
        ]
        if len(peers) < self.min_samples:
            return CONTINUE
        my_avg = float(np.mean(hist))
        if my_avg < float(np.median(peers)):
            return STOP if self.hard_stop else CONTINUE
        return CONTINUE
