"""The Tune event loop: multiplexes live trials, applies scheduler decisions.

Reference: `python/ray/tune/execution/trial_runner.py:1181` (`TrialRunner`,
event loop `step():1358`) + `ray_trial_executor.py:185`. Each trial's function
trainable runs inside one actor (Train's thread-session streams its reports);
the loop waits on the outstanding `next_result` futures of all running trials
(`ray_tpu.wait`), so a slow trial never blocks a fast one — the property
ASHA's asynchronous pruning depends on.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.result import Result
from ray_tpu.train._internal.session import DONE, ERROR, REPORT, SessionArgs
from ray_tpu.train._internal.worker_group import RayTrainWorker
from ray_tpu.tune.experiment import trial as trial_mod
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.schedulers.trial_scheduler import (
    CONTINUE,
    RESTART,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)


class TrialRunner:
    def __init__(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        trials: List[Trial],
        scheduler: Optional[TrialScheduler] = None,
        max_concurrent: Optional[int] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        stop: Any = None,  # metric-threshold dict | Stopper | callable
        experiment_name: str = "",
        searcher=None,
        num_samples: int = 0,
        trial_factory=None,
        experiment_dir: Optional[str] = None,
        callbacks=None,
    ):
        from ray_tpu.tune.callback import CallbackList

        self._callbacks = CallbackList(callbacks)
        # Monotonic event-loop step count passed to every callback hook
        # (reference: Callback `iteration` argument).
        self._iteration = 0
        self._train_fn = train_fn
        self.trials = trials
        # Adaptive mode: `searcher.suggest()` creates trials as capacity
        # frees (up to num_samples), so later configs condition on earlier
        # results (the reference's SearchGenerator behavior).
        self._searcher = searcher
        self._num_samples = num_samples
        self._trial_factory = trial_factory
        self._scheduler = scheduler or FIFOScheduler()
        self._max_concurrent = max_concurrent or 8
        self._resources = dict(resources_per_trial or {"CPU": 1.0})
        from ray_tpu.tune.stopper import Stopper, coerce_stopper

        stop = coerce_stopper(stop)
        self._stopper: Optional[Stopper] = (
            stop if isinstance(stop, Stopper) else None
        )
        self._stop = dict(stop or {}) if isinstance(stop, (dict, type(None))) else {}
        self._stop_all = False
        self._experiment_name = experiment_name
        self._actors: Dict[str, Any] = {}  # trial_id -> actor handle
        self._refs: Dict[Any, Trial] = {}  # outstanding next_result ref -> trial
        self._experiment_dir = experiment_dir
        for t in trials:
            self._scheduler.on_trial_add(self, t)

    def _save_state(self, force: bool = False) -> None:
        """Journal every trial's state to <experiment_dir>/experiment_state.json
        (atomic replace) so a killed driver can `Tuner.restore` (reference:
        `TrialRunner.checkpoint`, throttled like the reference's
        `checkpoint_period`). Lifecycle transitions force a write; per-report
        writes are rate-limited — the journal is O(all trials) JSON."""
        if self._experiment_dir is None:
            return
        now = time.time()
        if not force and now - getattr(self, "_last_journal", 0.0) < 2.0:
            return
        self._last_journal = now
        import json
        import os

        path = os.path.join(self._experiment_dir, "experiment_state.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"trials": [t.to_state() for t in self.trials]}, f)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — journaling must never kill the run
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # ------------------------------------------------------------------ launch
    def _actor_options(self) -> Dict[str, Any]:
        res = dict(self._resources)
        opts: Dict[str, Any] = {"num_cpus": res.pop("CPU", 1.0)}
        if "TPU" in res:
            opts["num_tpus"] = res.pop("TPU")
        if res:
            opts["resources"] = res
        return opts

    def _launch(self, trial: Trial) -> None:
        actor = ray_tpu.remote(RayTrainWorker).options(**self._actor_options()).remote()
        args = SessionArgs(
            train_fn=self._train_fn,
            config=dict(trial.config),
            world_rank=0,
            world_size=1,
            local_rank=0,
            local_world_size=1,
            node_rank=0,
            trial_name=trial.name,
            trial_id=trial.trial_id,
            trial_dir=trial.local_dir,
            experiment_name=self._experiment_name,
            checkpoint=trial.restore_checkpoint or trial.checkpoint,
        )
        ray_tpu.get(actor.init_session.remote(args))
        trial.restore_checkpoint = None
        trial.status = trial_mod.RUNNING
        self._actors[trial.trial_id] = actor
        self._refs[actor.next_result.remote()] = trial
        self._save_state(force=True)
        self._callbacks.fire(
            "on_trial_start", self._iteration, self.trials, trial
        )

    def _teardown(self, trial: Trial) -> None:
        actor = self._actors.pop(trial.trial_id, None)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        for ref, t in list(self._refs.items()):
            if t is trial:
                del self._refs[ref]

    # -------------------------------------------------------------------- run
    def _suggest_more(self) -> None:
        while (
            self._searcher is not None
            and len(self.trials) < self._num_samples
            and len(self._actors) < self._max_concurrent
        ):
            index = len(self.trials)
            trial = self._trial_factory(index)
            cfg = self._searcher.suggest(trial.trial_id)
            if cfg is None:
                self._num_samples = len(self.trials)
                return
            trial.config = dict(cfg)
            self.trials.append(trial)
            self._scheduler.on_trial_add(self, trial)
            self._launch(trial)

    def _complete(self, trial: Trial, error: bool = False) -> None:
        self._save_state(force=True)
        self._scheduler.on_trial_complete(self, trial)
        if self._searcher is not None:
            self._searcher.on_trial_complete(
                trial.trial_id, trial.last_result, error=error
            )
        self._callbacks.fire(
            "on_trial_error" if error else "on_trial_complete",
            self._iteration, self.trials, trial,
        )

    def run(self) -> None:
        self._callbacks.fire("setup")
        pending = [t for t in self.trials if t.status == trial_mod.PENDING]
        while pending or self._refs or (
            self._searcher is not None and len(self.trials) < self._num_samples
        ):
            if self._stop_all:
                # A Stopper ended the experiment: terminate everything live.
                for t in list(self._refs.values()):
                    t.status = trial_mod.TERMINATED
                    self._teardown(t)
                    self._complete(t)
                for t in pending:
                    t.status = trial_mod.TERMINATED
                pending.clear()
                self._num_samples = len(self.trials)
                continue
            while pending and len(self._actors) < self._max_concurrent:
                self._launch(pending.pop(0))
            self._suggest_more()
            if not self._refs:
                continue
            ready, _ = ray_tpu.wait(
                list(self._refs.keys()), num_returns=1, timeout=5.0
            )
            self._iteration += 1
            for ref in ready:
                trial = self._refs.pop(ref)
                try:
                    tr = ray_tpu.get(ref)
                except Exception as e:  # actor died
                    trial.status = trial_mod.ERROR
                    trial.error = str(e)
                    self._teardown(trial)
                    self._complete(trial, error=True)
                    continue
                if tr.type == ERROR:
                    trial.status = trial_mod.ERROR
                    trial.error = tr.error
                    self._teardown(trial)
                    self._complete(trial, error=True)
                elif tr.type == DONE:
                    trial.status = trial_mod.TERMINATED
                    self._teardown(trial)
                    self._complete(trial)
                else:  # REPORT
                    trial.num_results += 1
                    metrics = dict(tr.metrics or {})
                    metrics.setdefault("training_iteration", trial.num_results)
                    metrics.setdefault("trial_id", trial.trial_id)
                    metrics["config"] = dict(trial.config)
                    trial.last_result = metrics
                    if tr.checkpoint is not None:
                        trial.checkpoint_manager.register(tr.checkpoint, metrics)
                        self._callbacks.fire(
                            "on_checkpoint", self._iteration, self.trials,
                            trial, tr.checkpoint,
                        )
                    self._save_state()
                    self._callbacks.fire(
                        "on_trial_result", self._iteration, self.trials,
                        trial, metrics,
                    )
                    if self._should_stop(trial, metrics):
                        decision = STOP
                    else:
                        decision = self._scheduler.on_trial_result(self, trial, metrics)
                    if self._searcher is not None:
                        self._searcher.on_trial_result(trial.trial_id, metrics)
                    if decision == STOP:
                        trial.status = trial_mod.TERMINATED
                        self._teardown(trial)
                        self._complete(trial)
                    elif decision == RESTART:
                        trial.restarts += 1
                        self._teardown(trial)
                        self._launch(trial)
                    else:
                        actor = self._actors[trial.trial_id]
                        self._refs[actor.next_result.remote()] = trial
        self._callbacks.fire("on_experiment_end", self.trials)

    def _should_stop(self, trial: Trial, metrics: Dict[str, Any]) -> bool:
        if self._stopper is not None:
            should = self._stopper(trial.trial_id, metrics)
            # stop_all is consulted on EVERY result — even one that also
            # stops its own trial — or an experiment-wide stop could be
            # missed whenever the per-trial check fires first.
            if self._stopper.stop_all():
                self._stop_all = True
                return True
            if should:
                return True
        for k, v in self._stop.items():
            if k in metrics and metrics[k] >= v:
                return True
        return False

    # ----------------------------------------------------------------- results
    def results(self) -> List[Result]:
        out = []
        for t in self.trials:
            err = None
            if t.status == trial_mod.ERROR:
                err = RuntimeError(t.error or "trial failed")
            out.append(
                Result(
                    metrics=t.last_result,
                    checkpoint=t.checkpoint_manager.best_checkpoint(),
                    error=err,
                    path=t.local_dir,
                    best_checkpoints=t.checkpoint_manager.best_checkpoints(),
                )
            )
        return out
