"""Tuner: the user-facing sweep API.

Reference: `python/ray/tune/tuner.py` (`Tuner(trainable, param_space,
tune_config, run_config)`, `.fit() -> ResultGrid`). Accepts a plain function
trainable `fn(config)` (reporting via `ray_tpu.air.session.report`) or a
`BaseTrainer` (its `as_trainable()`; `param_space["train_loop_config"]`
overrides the trainer's loop config per trial — the reference's Trainer+Tuner
composition, `base_trainer.py:557`).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.air.config import RunConfig
from ray_tpu.train.base_trainer import BaseTrainer, default_storage_path
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.tune_config import TuneConfig


class Tuner:
    def __init__(
        self,
        trainable: Union[Callable[[Dict[str, Any]], None], BaseTrainer],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        from ray_tpu._private import usage

        usage.record_library_usage("tune")
        self._trainable = trainable
        self._param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def _resolve_trainable(self) -> Callable[[Dict[str, Any]], None]:
        if isinstance(self._trainable, BaseTrainer):
            return self._trainable.as_trainable()
        if callable(self._trainable):
            return self._trainable
        raise TypeError(f"invalid trainable: {type(self._trainable)}")

    def fit(self) -> ResultGrid:
        import ray_tpu
        from ray_tpu._private.worker import _auto_init

        _auto_init()
        name = self.run_config.name or f"tune_{int(time.time())}"
        base = self.run_config.storage_path or default_storage_path()
        experiment_dir = os.path.join(os.path.expanduser(base), name)
        os.makedirs(experiment_dir, exist_ok=True)

        searcher = self.tune_config.search_alg
        if searcher is not None:
            searcher.set_search_properties(
                self.tune_config.metric,
                self.tune_config.mode,
                self._param_space,
                seed=self.tune_config.search_seed,
            )
            trials = []
        else:
            gen = BasicVariantGenerator(seed=self.tune_config.search_seed)
            configs = list(
                gen.generate(self._param_space, self.tune_config.num_samples)
            )
            if not configs:
                configs = [{}]
            trials = [
                Trial(cfg, experiment_dir, i, experiment_name=name)
                for i, cfg in enumerate(configs)
            ]

        scheduler = self.tune_config.scheduler
        if scheduler is not None and hasattr(scheduler, "set_objective"):
            scheduler.set_objective(self.tune_config.metric, self.tune_config.mode)

        max_conc = self.tune_config.max_concurrent_trials
        if max_conc is None:
            # Don't oversubscribe: bound by what the cluster can actually run.
            cpus = ray_tpu.cluster_resources().get("CPU", 1.0)
            per_trial = self.tune_config.resources_per_trial.get("CPU", 1.0) or 1.0
            max_conc = max(1, int(cpus / per_trial))

        runner = TrialRunner(
            self._resolve_trainable(),
            trials,
            scheduler=scheduler,
            max_concurrent=max_conc,
            resources_per_trial=self.tune_config.resources_per_trial,
            stop=self.run_config.stop,
            experiment_name=name,
            searcher=searcher,
            num_samples=self.tune_config.num_samples if searcher is not None else 0,
            trial_factory=lambda i: Trial({}, experiment_dir, i, experiment_name=name),
        )
        runner.run()
        return ResultGrid(
            runner.results(), metric=self.tune_config.metric, mode=self.tune_config.mode
        )
