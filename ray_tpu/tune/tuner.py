"""Tuner: the user-facing sweep API.

Reference: `python/ray/tune/tuner.py` (`Tuner(trainable, param_space,
tune_config, run_config)`, `.fit() -> ResultGrid`). Accepts a plain function
trainable `fn(config)` (reporting via `ray_tpu.air.session.report`) or a
`BaseTrainer` (its `as_trainable()`; `param_space["train_loop_config"]`
overrides the trainer's loop config per trial — the reference's Trainer+Tuner
composition, `base_trainer.py:557`).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.air.config import RunConfig
from ray_tpu.train.base_trainer import BaseTrainer, default_storage_path
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.experiment.trial import Trial
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.tune_config import TuneConfig


class Tuner:
    def __init__(
        self,
        trainable: Union[Callable[[Dict[str, Any]], None], BaseTrainer],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        from ray_tpu._private import usage

        usage.record_library_usage("tune")
        self._trainable = trainable
        self._param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        # Set by Tuner.restore(): resume journaled trials instead of starting
        # fresh ones.
        self._restore_dir: Optional[str] = None
        self._resume_errored = False

    @classmethod
    def restore(
        cls,
        path: str,
        trainable: Optional[Union[Callable, BaseTrainer]] = None,
        *,
        resume_errored: bool = False,
    ) -> "Tuner":
        """Resume a killed/interrupted experiment from its directory
        (reference: `python/ray/tune/tuner.py:175 Tuner.restore`).

        Finished trials keep their journaled results and checkpoints;
        unfinished trials re-run, resuming from their latest checkpoint;
        errored trials re-run only with `resume_errored=True`. `trainable`
        may be re-supplied (required if the saved one fails to load)."""
        import pickle

        path = os.path.expanduser(path)
        state_file = os.path.join(path, "experiment_state.json")
        if not os.path.exists(state_file):
            raise FileNotFoundError(
                f"no experiment journal at {state_file}; was this experiment "
                "run by Tuner.fit()?"
            )
        spec: Dict[str, Any] = {}
        try:
            with open(os.path.join(path, "tuner.pkl"), "rb") as f:
                spec = pickle.load(f)
        except Exception:  # noqa: BLE001 — trainable may be passed anew
            if trainable is None:
                raise ValueError(
                    "could not load the saved tuner spec; pass `trainable=`"
                ) from None
            import warnings

            warnings.warn(
                "tuner.pkl could not be loaded: restoring with DEFAULT "
                "TuneConfig/RunConfig (metric/mode/num_samples/stop from the "
                "original run are lost)",
                stacklevel=2,
            )
        if trainable is None:
            trainable = spec.get("trainable")
        if trainable is None:
            raise ValueError("saved spec has no trainable; pass `trainable=`")
        tuner = cls(
            trainable,
            param_space=spec.get("param_space"),
            tune_config=spec.get("tune_config"),
            run_config=spec.get("run_config"),
        )
        tuner.run_config.name = os.path.basename(path.rstrip("/"))
        tuner.run_config.storage_path = os.path.dirname(path.rstrip("/"))
        tuner._restore_dir = path
        tuner._resume_errored = resume_errored
        return tuner

    @staticmethod
    def can_restore(path: str) -> bool:
        return os.path.exists(
            os.path.join(os.path.expanduser(path), "experiment_state.json")
        )

    def _resolve_trainable(self) -> Callable[[Dict[str, Any]], None]:
        if isinstance(self._trainable, BaseTrainer):
            return self._trainable.as_trainable()
        if callable(self._trainable):
            return self._trainable
        raise TypeError(f"invalid trainable: {type(self._trainable)}")

    def fit(self) -> ResultGrid:
        import ray_tpu
        from ray_tpu._private.worker import _auto_init

        _auto_init()
        name = self.run_config.name or f"tune_{int(time.time())}"
        base = self.run_config.storage_path or default_storage_path()
        experiment_dir = os.path.join(os.path.expanduser(base), name)
        os.makedirs(experiment_dir, exist_ok=True)
        self._save_spec(experiment_dir)

        searcher = self.tune_config.search_alg
        if self._restore_dir is not None:
            trials = self._restored_trials(name)
            if searcher is not None:
                # Journaled trials carry their configs; the searcher (fresh
                # state — observations are not replayed) suggests only the
                # remaining num_samples - len(trials) samples.
                searcher.set_search_properties(
                    self.tune_config.metric,
                    self.tune_config.mode,
                    self._param_space,
                    seed=self.tune_config.search_seed,
                )
        elif searcher is not None:
            searcher.set_search_properties(
                self.tune_config.metric,
                self.tune_config.mode,
                self._param_space,
                seed=self.tune_config.search_seed,
            )
            trials = []
        else:
            gen = BasicVariantGenerator(seed=self.tune_config.search_seed)
            configs = list(
                gen.generate(self._param_space, self.tune_config.num_samples)
            )
            if not configs:
                configs = [{}]
            trials = [
                Trial(cfg, experiment_dir, i, experiment_name=name)
                for i, cfg in enumerate(configs)
            ]

        scheduler = self.tune_config.scheduler
        if scheduler is not None and hasattr(scheduler, "set_objective"):
            scheduler.set_objective(self.tune_config.metric, self.tune_config.mode)

        max_conc = self.tune_config.max_concurrent_trials
        if max_conc is None:
            # Don't oversubscribe: bound by what the cluster can actually run.
            cpus = ray_tpu.cluster_resources().get("CPU", 1.0)
            per_trial = self.tune_config.resources_per_trial.get("CPU", 1.0) or 1.0
            max_conc = max(1, int(cpus / per_trial))

        runner = TrialRunner(
            self._resolve_trainable(),
            trials,
            scheduler=scheduler,
            max_concurrent=max_conc,
            resources_per_trial=self.tune_config.resources_per_trial,
            stop=self.run_config.stop,
            experiment_name=name,
            searcher=searcher,
            num_samples=self.tune_config.num_samples if searcher is not None else 0,
            trial_factory=lambda i: Trial({}, experiment_dir, i, experiment_name=name),
            experiment_dir=experiment_dir,
            callbacks=self.run_config.callbacks,
        )
        runner.run()
        return ResultGrid(
            runner.results(), metric=self.tune_config.metric, mode=self.tune_config.mode
        )

    # ---------------------------------------------------------------- resume
    def _save_spec(self, experiment_dir: str) -> None:
        """Persist the tuner spec so `Tuner.restore(path)` can rebuild it."""
        from ray_tpu._private import serialization

        try:
            blob = serialization.dumps({
                "trainable": self._trainable,
                "param_space": self._param_space,
                "tune_config": self.tune_config,
                "run_config": self.run_config,
            })
            tmp = os.path.join(experiment_dir, f"tuner.pkl.tmp.{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(experiment_dir, "tuner.pkl"))
        except Exception:  # noqa: BLE001 — unpicklable trainable: restore
            pass  # will require re-passing trainable=

    def _restored_trials(self, name: str):
        """Rebuild trials from the experiment journal: finished trials keep
        results/checkpoints; unfinished ones go PENDING and resume from their
        latest persisted checkpoint."""
        import json

        from ray_tpu.tune.experiment import trial as trial_mod

        with open(os.path.join(self._restore_dir, "experiment_state.json")) as f:
            states = json.load(f)["trials"]
        trials = []
        for st in states:
            t = Trial.from_state(st, self._restore_dir, experiment_name=name)
            rerun = t.status in (trial_mod.PENDING, trial_mod.RUNNING) or (
                t.status == trial_mod.ERROR and self._resume_errored
            )
            if rerun:
                t.status = trial_mod.PENDING
                t.error = None
                t.restore_checkpoint = t.checkpoint  # latest persisted, if any
            trials.append(t)
        return trials


def with_parameters(trainable, **kwargs):
    """Bind large objects to a trainable via the object store (reference:
    `python/ray/tune/trainable/util.py with_parameters`): each value is put
    ONCE and fetched zero-copy per trial, instead of pickling into every
    trial's config/spec."""
    import ray_tpu

    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    def inner(config):
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        return trainable(config, **resolved)

    inner.__name__ = getattr(trainable, "__name__", "trainable")
    return inner
