"""Sharded train-state + train-step factory for the model zoo.

The SPMD recipe (scaling-book style, SURVEY.md §7): params are initialized
*under jit with explicit out_shardings* (so big models never materialize
unsharded), the optimizer state inherits param shardings through propagation,
and the train step is a single jitted function with donated state — XLA inserts
the DP gradient all-reduce / FSDP all-gathers / TP collectives from the sharding
annotations alone. Loss-parity note: this is the exact computation a bare-JAX
script would run; the framework adds no per-step Python between device
dispatches (the reference's "Ray adds ~0% overhead over DDP" property).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel import ShardingRules, batch_spec
from ray_tpu.models import gpt


def model_for(config):
    """Dispatch a config dataclass to its model module (gpt, llama, resnet,
    ...), so one TrainState/step factory serves the whole zoo."""
    from ray_tpu.models import llama, resnet

    if isinstance(config, llama.LlamaConfig):
        return llama
    if isinstance(config, resnet.ResNetConfig):
        return resnet
    return gpt


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def param_shardings(config, mesh, rules: ShardingRules):
    model = model_for(config)
    axes = model.param_logical_axes(config)
    shapes = jax.eval_shape(lambda: model.init_params(config, jax.random.PRNGKey(0)))
    return jax.tree.map(
        lambda ax, s: rules.sharding(mesh, ax, shape=s.shape),
        axes,
        shapes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )


def create_train_state(
    config,
    key,
    optimizer,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> TrainState:
    """Initialize params (sharded, under jit) + optimizer state."""
    if mesh is not None:
        rules = rules or ShardingRules()
        shardings = param_shardings(config, mesh, rules)
        init = jax.jit(lambda k: model_for(config).init_params(config, k), out_shardings=shardings)
    else:
        init = jax.jit(lambda k: model_for(config).init_params(config, k))
    params = init(key)
    # Optimizer state (adam mu/nu) inherits the param shardings by propagation.
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def make_train_step(
    config,
    optimizer,
    mesh=None,
    attention_fn: Optional[Callable] = None,
    donate: bool = True,
) -> Callable[[TrainState, Dict[str, Any]], Tuple[TrainState, Dict[str, Any]]]:
    """One fused SPMD update: loss -> grads -> optimizer -> new state."""

    base_rng = jax.random.PRNGKey(0x5eed)

    def step_fn(state: TrainState, batch):
        dropout_rng = (
            jax.random.fold_in(base_rng, state.step)
            if getattr(config, "dropout", 0) > 0
            else None
        )

        def loss_of(p):
            return model_for(config).loss_fn(
                p, batch, config, attention_fn, dropout_rng, mesh=mesh
            )

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        import optax

        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": gnorm, "step": new_state.step}

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def shard_batch(batch: Dict[str, Any], mesh):
    """Place a host batch onto the mesh with the canonical batch sharding
    (batch dim over (data, fsdp), sequence over context — `parallel.batch_spec`)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        if x.ndim == 2:
            spec = batch_spec()  # (batch over data/fsdp, sequence over context)
        else:
            # 1-D labels and N-D image tensors: only the batch dim shards
            # (context parallelism is a sequence-axis concept; image H/W must
            # not land on it).
            spec = P(("data", "fsdp"))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def default_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 0,
    total_steps: int = 0,
):
    """AdamW with cosine schedule + global-norm clipping (GPT-2 recipe)."""
    import optax

    if total_steps:
        lr = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, max(warmup_steps, 1), total_steps
        )
    else:
        lr = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(lr, b1=b1, b2=b2, weight_decay=weight_decay),
    )
