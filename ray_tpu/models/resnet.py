"""ResNet family, TPU-first: the vision member of the model zoo.

Reference workload: Ray Train data-parallel ResNet-50 on ImageNet
(`release/train_tests/` / BASELINE config #3 — the reference itself ships no
model code). Design follows the zoo's rules (`models/gpt.py`):
 - plain pytree params with per-leaf logical axes; DP/FSDP come from
   `parallel.ShardingRules` at trainer level.
 - NHWC layout (TPU-native conv layout; channels on the 128-lane minor dim).
 - GroupNorm instead of BatchNorm: normalization is then a pure per-example
   function — no mutable running statistics threading through the train
   state, no cross-replica stat sync — and the train step stays a single
   donated jit like every other model (ResNet+GN matches BN accuracy at
   ImageNet scale; Wu & He, "Group Normalization").
 - bf16 conv/matmul compute, f32 norms and logits.

Supports the standard depths via bottleneck (50/101/152) and basic (18/34)
blocks; `resnet50()` is the benchmark preset.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    # Stage depths, e.g. (3, 4, 6, 3) for ResNet-50.
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    groupnorm_groups: int = 32
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def expansion(self) -> int:
        return 4 if self.bottleneck else 1

    # ---- presets ----
    @classmethod
    def resnet18(cls, **kw):
        return cls(stage_sizes=(2, 2, 2, 2), bottleneck=False, **kw)

    @classmethod
    def resnet34(cls, **kw):
        return cls(stage_sizes=(3, 4, 6, 3), bottleneck=False, **kw)

    @classmethod
    def resnet50(cls, **kw):
        return cls(stage_sizes=(3, 4, 6, 3), bottleneck=True, **kw)

    @classmethod
    def resnet101(cls, **kw):
        return cls(stage_sizes=(3, 4, 23, 3), bottleneck=True, **kw)

    @classmethod
    def nano(cls, **kw):
        """Tiny config for CPU tests (CIFAR-shaped inputs train in seconds)."""
        kw.setdefault("num_classes", 10)
        kw.setdefault("width", 8)
        kw.setdefault("groupnorm_groups", 4)
        return cls(stage_sizes=(1, 1), bottleneck=False, **kw)


def _conv_init(key, shape, pd):
    """He-normal over fan_in (kh * kw * cin)."""
    fan_in = shape[0] * shape[1] * shape[2]
    return (jax.random.normal(key, shape) * math.sqrt(2.0 / fan_in)).astype(pd)


def _stage_channels(config: ResNetConfig) -> List[int]:
    return [config.width * (2**i) for i in range(len(config.stage_sizes))]


def init_params(config: ResNetConfig, key) -> Dict[str, Any]:
    pd = config.param_dtype
    keys = iter(jax.random.split(key, 1024))
    params: Dict[str, Any] = {
        "stem": {
            "conv": _conv_init(next(keys), (7, 7, 3, config.width), pd),
            "gn_scale": jnp.ones((config.width,), pd),
            "gn_bias": jnp.zeros((config.width,), pd),
        }
    }
    cin = config.width
    for si, (n_blocks, ch) in enumerate(zip(config.stage_sizes, _stage_channels(config))):
        blocks = []
        cout = ch * config.expansion
        for bi in range(n_blocks):
            b: Dict[str, Any] = {}
            if config.bottleneck:
                b["conv1"] = _conv_init(next(keys), (1, 1, cin, ch), pd)
                b["conv2"] = _conv_init(next(keys), (3, 3, ch, ch), pd)
                b["conv3"] = _conv_init(next(keys), (1, 1, ch, cout), pd)
                norms = 3
            else:
                b["conv1"] = _conv_init(next(keys), (3, 3, cin, ch), pd)
                b["conv2"] = _conv_init(next(keys), (3, 3, ch, cout), pd)
                norms = 2
            # Final-norm scale initialized to zero (the standard residual-
            # friendly init: each block starts as identity).
            sizes = [ch, ch, cout] if config.bottleneck else [ch, cout]
            for ni, c in enumerate(sizes):
                b[f"gn{ni + 1}_scale"] = (
                    jnp.zeros((c,), pd) if ni == norms - 1 else jnp.ones((c,), pd)
                )
                b[f"gn{ni + 1}_bias"] = jnp.zeros((c,), pd)
            if cin != cout:
                # Covers every stride-2 block too: stage channels double, so
                # the first block of each later stage always changes width.
                b["proj"] = _conv_init(next(keys), (1, 1, cin, cout), pd)
            blocks.append(b)
            cin = cout
        params[f"stage{si}"] = blocks
    params["head"] = {
        "w": (jax.random.normal(next(keys), (cin, config.num_classes)) * 0.01).astype(pd),
        "b": jnp.zeros((config.num_classes,), pd),
    }
    return params


def param_logical_axes(config: ResNetConfig) -> Dict[str, Any]:
    """Conv kernels shard their output-channel dim over `mlp` (FSDP-style);
    the classifier head shards embed -> vocab like an LM head. Derived from
    the param tree itself so the structure always matches exactly (proj
    kernels exist only on downsampling blocks)."""
    shapes = init_shapes(config)

    def ax(path, leaf):
        names = [getattr(k, "key", getattr(k, "idx", None)) for k in path]
        if names[-2:] == ["head", "w"]:
            return ("embed", "vocab")
        if leaf.ndim == 4:  # conv kernel (kh, kw, cin, cout)
            return (None, None, None, "mlp")
        return (None,) * leaf.ndim

    return jax.tree_util.tree_map_with_path(ax, shapes)


def num_params(config: ResNetConfig) -> int:
    return sum(p.size for p in jax.tree.leaves(init_shapes(config)))


def init_shapes(config: ResNetConfig):
    return jax.eval_shape(lambda: init_params(config, jax.random.PRNGKey(0)))


# --------------------------------------------------------------------------- forward
def _group_norm(x, scale, bias, groups, eps=1e-5):
    N, H, W, C = x.shape
    g = min(groups, C)
    while C % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(N, H, W, g, C // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return xf.reshape(N, H, W, C) * scale + bias


def _conv(x, w, stride=1, cdt=None):
    return jax.lax.conv_general_dilated(
        x.astype(cdt),
        w.astype(cdt),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )


def _block_fwd(x, b, config: ResNetConfig, stride: int):
    cdt = config.dtype
    g = config.groupnorm_groups
    residual = x
    if config.bottleneck:
        h = _conv(x, b["conv1"], 1, cdt)
        h = jax.nn.relu(_group_norm(h, b["gn1_scale"], b["gn1_bias"], g))
        h = _conv(h, b["conv2"], stride, cdt)
        h = jax.nn.relu(_group_norm(h, b["gn2_scale"], b["gn2_bias"], g))
        h = _conv(h, b["conv3"], 1, cdt)
        h = _group_norm(h, b["gn3_scale"], b["gn3_bias"], g)
    else:
        h = _conv(x, b["conv1"], stride, cdt)
        h = jax.nn.relu(_group_norm(h, b["gn1_scale"], b["gn1_bias"], g))
        h = _conv(h, b["conv2"], 1, cdt)
        h = _group_norm(h, b["gn2_scale"], b["gn2_bias"], g)
    if "proj" in b:
        residual = _conv(x, b["proj"], stride, cdt)
    else:
        # Identity residual: init guarantees a proj whenever shape changes.
        assert stride == 1, "stride-2 block without a projection kernel"
    return jax.nn.relu(h + residual.astype(jnp.float32)).astype(cdt)


def forward(
    params: Dict[str, Any],
    images,  # (B, H, W, 3) float
    config: ResNetConfig,
    attention_fn=None,  # API parity with the LM families (unused)
    dropout_rng=None,
    mesh=None,
    num_microbatches=None,
    return_aux: bool = False,
):
    """Class logits (B, num_classes) in float32."""
    del attention_fn, dropout_rng, mesh, num_microbatches
    cdt = config.dtype
    x = _conv(images, params["stem"]["conv"], 2, cdt)
    x = jax.nn.relu(
        _group_norm(x, params["stem"]["gn_scale"], params["stem"]["gn_bias"],
                    config.groupnorm_groups)
    ).astype(cdt)
    # 3x3 max-pool stride 2 (stem), as in the standard architecture.
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for si in range(len(config.stage_sizes)):
        for bi, b in enumerate(params[f"stage{si}"]):
            stride = 2 if (si > 0 and bi == 0) else 1
            x = _block_fwd(x, b, config, stride)
    x = x.astype(jnp.float32).mean(axis=(1, 2))  # global average pool
    logits = jnp.einsum(
        "bc,cn->bn", x.astype(cdt), params["head"]["w"].astype(cdt),
        preferred_element_type=jnp.float32,
    ) + params["head"]["b"].astype(jnp.float32)
    if return_aux:
        return logits, jnp.zeros((), jnp.float32)
    return logits


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, Any],  # {"images": (B,H,W,3), "labels": (B,)}
    config: ResNetConfig,
    attention_fn=None,
    dropout_rng=None,
    mesh=None,
    num_microbatches=None,
):
    """Softmax cross entropy over classes (mean over the batch)."""
    logits = forward(params, batch["images"], config, attention_fn, dropout_rng, mesh)
    labels = batch["labels"]
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    at = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return (lse - at).mean()
