"""Mixture-of-Experts MLP with expert parallelism over the `expert` mesh axis.

No reference equivalent (SURVEY.md §2: EP "NO") — designed TPU-first in the
GShard/Switch style: routing is expressed as DENSE one-hot dispatch/combine
einsums with a static capacity, so the whole layer is three large matmuls the
MXU loves, and sharding the expert dim over the `expert` axis makes XLA insert
the token all-to-all automatically (no ragged transfers, no dynamic shapes).

Top-1 (Switch) routing with capacity factor: tokens over an expert's capacity
are dropped to the residual path (standard Switch behavior; static shapes are
what keeps this jit-compilable). The auxiliary load-balancing loss
(mean(router_prob) . mean(assignment) * E) pushes the router toward uniform
expert usage.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def moe_capacity(num_tokens: int, num_experts: int, capacity_factor: float) -> int:
    import math

    return max(math.ceil(num_tokens * capacity_factor / num_experts), 1)


def moe_mlp(
    x,  # (B, S, D) activations, config.dtype
    router_w,  # (D, E) f32
    fc_w,  # (E, D, F)
    fc_b,  # (E, F)
    proj_w,  # (E, F, D)
    proj_b,  # (E, D)
    capacity_factor: float = 1.25,
) -> Tuple[Any, Any]:
    """Returns (out (B,S,D), aux_loss scalar).

    GShard-style GROUPED routing: each batch row is a routing group with its
    own per-expert capacity C = ceil(S/E * factor). The dispatch/combine
    tensors are (B, S, E, C) — linear in tokens (E*C ~ S), not the quadratic
    (N, E, N/E) a global top-1 would produce — and the capacity cumsum runs
    per group, so with batch sharded over `data` it never serializes across
    shards. Expert buffers are (E, B*C, D) with the expert dim sharded over
    the `expert` axis; XLA inserts the token all-to-alls around the per-expert
    matmuls."""
    B, S, D = x.shape
    E = router_w.shape[1]
    C = moe_capacity(S, E, capacity_factor)
    cdt = x.dtype

    # Router in f32 for stable softmax.
    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # (B, S, E)
    expert_idx = jnp.argmax(probs, axis=-1)  # (B, S) top-1 (Switch)
    gate = jnp.take_along_axis(probs, expert_idx[..., None], axis=-1)[..., 0]  # (B, S)

    # Per-group capacity bucketing: token's slot in its expert's queue.
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (B, S, E)
    position = jnp.cumsum(onehot, axis=1) * onehot  # 1-based slot within group
    within_cap = (position > 0) & (position <= C)
    slot = jnp.sum((position - 1) * onehot, axis=-1)  # (B, S)
    keep = jnp.any(within_cap, axis=-1)  # (B, S)

    # Dense dispatch/combine (B, S, E, C): linear in tokens.
    dispatch = (
        jax.nn.one_hot(expert_idx, E, dtype=cdt)[..., None]
        * jax.nn.one_hot(slot, C, dtype=cdt)[..., None, :]
        * keep[..., None, None].astype(cdt)
    )
    combine = dispatch * gate.astype(cdt)[..., None, None]

    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x)  # all-to-all under EP
    expert_in = expert_in.reshape(E, B * C, D)
    h = jnp.einsum("egd,edf->egf", expert_in, fc_w.astype(cdt)) + fc_b.astype(cdt)[:, None, :]
    h = jax.nn.gelu(h)
    h = jnp.einsum("egf,efd->egd", h, proj_w.astype(cdt)) + proj_b.astype(cdt)[:, None, :]
    h = h.reshape(E, B, C, D)
    out = jnp.einsum("bsec,ebcd->bsd", combine, h)  # all-to-all back

    # Switch aux loss: E * sum_e mean_tokens(assignment_e) * mean_tokens(prob_e).
    assign_frac = jnp.mean(onehot.astype(jnp.float32), axis=(0, 1))  # (E,)
    prob_frac = jnp.mean(probs, axis=(0, 1))  # (E,)
    aux = E * jnp.sum(assign_frac * prob_frac)

    return out, aux


def init_moe_params(key, n_layer: int, d_model: int, ff_dim: int, n_experts: int, param_dtype):
    """Stacked per-layer MoE params: router + per-expert FFN weights."""
    import math

    k1, k2, k3 = jax.random.split(key, 3)
    std = 0.02
    proj_std = std / math.sqrt(2 * n_layer)
    return {
        "router_w": (jax.random.normal(k1, (n_layer, d_model, n_experts)) * std).astype(param_dtype),
        "fc_w": (jax.random.normal(k2, (n_layer, n_experts, d_model, ff_dim)) * std).astype(param_dtype),
        "fc_b": jnp.zeros((n_layer, n_experts, ff_dim), param_dtype),
        "proj_w": (jax.random.normal(k3, (n_layer, n_experts, ff_dim, d_model)) * proj_std).astype(param_dtype),
        "proj_b": jnp.zeros((n_layer, n_experts, d_model), param_dtype),
    }


def moe_param_logical_axes() -> Dict[str, Tuple]:
    return {
        "router_w": ("layers", "embed", None),
        "fc_w": ("layers", "expert", "embed", "mlp"),
        "fc_b": ("layers", "expert", "mlp"),
        "proj_w": ("layers", "expert", "mlp", "embed"),
        "proj_b": ("layers", "expert", "embed"),
    }
