"""GPT-2 family, TPU-first: the flagship model for the Train/bench path
(BASELINE.md north star: data-parallel GPT-2 at >=40% MFU).

Design choices for the MXU/XLA:
 - params are a plain pytree with per-leaf *logical* axis names; placement is
   decided by `parallel.ShardingRules` at trainer level (DP/FSDP/TP without
   touching the model).
 - per-layer params are stacked on a leading "layers" dim and the forward scans
   over them (`lax.scan`): compile time is O(1) in depth, and remat
   (`jax.checkpoint`) wraps the scanned block to trade FLOPs for HBM.
 - activations/matmuls in bfloat16, params & softmax/logits in float32.
 - attention: pallas flash kernel on TPU, plain XLA elsewhere, ring attention
   (context parallelism) injectable via `attention_fn`.
 - vocab padded to a multiple of 128 so the logits matmul tiles the MXU.

The reference has no model code (it is the distributed substrate); the
equivalent user-facing artifact is its GPT-2 release benchmark
(`/root/reference/release/air_tests/air_benchmarks/` HF-GPT-2 workloads).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # 50257 padded up to a multiple of 128
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 0  # 0 -> 4 * d_model
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # None recomputes everything in the block; "dots" saves matmul outputs
    # across the remat boundary (less recompute, more memory); "save_attn"
    # remats the projections/MLP but keeps attention OUT of the remat region,
    # so the flash kernel (the most expensive op per byte saved) never
    # recomputes — q/k/v/o/lse are stored instead (~100MB/layer at B=16
    # S=1024 d=768 bf16).
    remat_policy: Optional[str] = "save_attn"
    attention: str = "auto"  # auto | flash | xla
    # Applied to embeddings and both residual branches when a dropout_rng is
    # passed to forward()/loss_fn (GPT-2 used 0.1; modern pretraining uses 0).
    dropout: float = 0.0
    # Mixture-of-experts: >0 replaces every block's dense MLP with a Switch
    # (top-1) MoE of this many experts, sharded over the `expert` mesh axis
    # (models/moe.py). 0 = dense.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    # ---- presets ----
    @classmethod
    def gpt2_small(cls, **kw):
        return cls(n_layer=12, n_head=12, d_model=768, **kw)

    @classmethod
    def gpt2_medium(cls, **kw):
        return cls(n_layer=24, n_head=16, d_model=1024, **kw)

    @classmethod
    def gpt2_large(cls, **kw):
        return cls(n_layer=36, n_head=20, d_model=1280, **kw)

    @classmethod
    def gpt2_xl(cls, **kw):
        return cls(n_layer=48, n_head=25, d_model=1600, **kw)

    @classmethod
    def nano(cls, **kw):
        """Tiny config for CPU tests."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 128)
        return cls(n_layer=2, n_head=2, d_model=64, **kw)


def num_params(config: GPTConfig) -> int:
    d, L, V, F = config.d_model, config.n_layer, config.vocab_size, config.ff_dim
    E = config.moe_experts
    if E:
        mlp = d * E + E * (d * F + F + F * d + d)  # router + per-expert FFNs
    else:
        mlp = d * F + F + F * d + d
    per_layer = (
        3 * d * d + 3 * d  # qkv
        + d * d + d        # attn out
        + mlp
        + 4 * d            # 2 layernorms
    )
    return V * d + config.max_seq_len * d + L * per_layer + 2 * d


def train_flops_per_token(config: GPTConfig, seq_len: int) -> float:
    """6*N matmul flops + attention term, the standard MFU accounting.

    The tied wte is counted once: as embedding table it costs no matmul flops,
    as the logits head it does — num_params already includes it exactly once.
    """
    attn = 12 * config.n_layer * config.d_model * seq_len  # fwd+bwd qk+pv
    return 6.0 * num_params(config) + attn


# --------------------------------------------------------------------------- init
def init_params(config: GPTConfig, key) -> Dict[str, Any]:
    d, L, V, F = config.d_model, config.n_layer, config.vocab_size, config.ff_dim
    nh, hd = config.n_head, config.head_dim
    k = iter(jax.random.split(key, 16))
    std = 0.02
    proj_std = std / math.sqrt(2 * L)  # GPT-2 residual-scaled init
    pd = config.param_dtype

    def norm(key, shape, s):
        return (jax.random.normal(key, shape) * s).astype(pd)

    blocks = {
        "ln1_scale": jnp.ones((L, d), pd),
        "ln1_bias": jnp.zeros((L, d), pd),
        "qkv_w": norm(next(k), (L, d, 3, nh, hd), std),
        "qkv_b": jnp.zeros((L, 3, nh, hd), pd),
        "out_w": norm(next(k), (L, nh, hd, d), proj_std),
        "out_b": jnp.zeros((L, d), pd),
        "ln2_scale": jnp.ones((L, d), pd),
        "ln2_bias": jnp.zeros((L, d), pd),
    }
    if config.moe_experts:
        from ray_tpu.models.moe import init_moe_params

        blocks["moe"] = init_moe_params(
            next(k), L, d, F, config.moe_experts, pd
        )
    else:
        blocks.update(
            {
                "fc_w": norm(next(k), (L, d, F), std),
                "fc_b": jnp.zeros((L, F), pd),
                "proj_w": norm(next(k), (L, F, d), proj_std),
                "proj_b": jnp.zeros((L, d), pd),
            }
        )
    params = {
        "wte": norm(next(k), (V, d), std),
        "wpe": norm(next(k), (config.max_seq_len, d), std),
        "blocks": blocks,
        "lnf_scale": jnp.ones((d,), pd),
        "lnf_bias": jnp.zeros((d,), pd),
    }
    return params


def param_logical_axes(config: GPTConfig) -> Dict[str, Any]:
    """Per-leaf logical axis names, consumed by parallel.ShardingRules."""
    blocks = {
        "ln1_scale": ("layers", None),
        "ln1_bias": ("layers", None),
        "qkv_w": ("layers", "embed", None, "heads", None),
        "qkv_b": ("layers", None, "heads", None),
        "out_w": ("layers", "heads", None, "embed"),
        "out_b": ("layers", None),
        "ln2_scale": ("layers", None),
        "ln2_bias": ("layers", None),
    }
    if config.moe_experts:
        from ray_tpu.models.moe import moe_param_logical_axes

        blocks["moe"] = moe_param_logical_axes()
    else:
        blocks.update(
            {
                "fc_w": ("layers", "embed", "mlp"),
                "fc_b": ("layers", "mlp"),
                "proj_w": ("layers", "mlp", "embed"),
                "proj_b": ("layers", None),
            }
        )
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": blocks,
        "lnf_scale": (None,),
        "lnf_bias": (None,),
    }


# --------------------------------------------------------------------------- forward
def _layer_norm(x, scale, bias, eps=1e-5):
    x = x.astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return ((x - mean) * jax.lax.rsqrt(var + eps) * scale + bias)




def _dropout(x, rate: float, rng):
    if rng is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)


def _block(x, layer, config: GPTConfig, attention_fn, drop_rng=None, sub_remat=False):
    """One transformer block. x: (B, S, D) in config.dtype.
    Returns (x, aux) — aux is the MoE load-balance loss (0.0 when dense).

    With sub_remat ("save_attn" policy), the qkv-projection and the
    outproj/MLP halves are individually remat'ed while the attention call
    between them is not: its residuals (q/k/v/o and the kernel's lse) are
    saved, so the backward pass never re-runs the attention kernel."""
    cdt = config.dtype
    r1 = r2 = None
    if drop_rng is not None and config.dropout > 0:
        r1, r2 = jax.random.split(drop_rng)

    def qkv_part(x, layer):
        h = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"]).astype(cdt)
        qkv = jnp.einsum("bsd,dcnh->bscnh", h, layer["qkv_w"].astype(cdt)) + layer[
            "qkv_b"
        ].astype(cdt)
        return tuple(jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))  # (B, nh, S, hd)

    def out_mlp_part(x, o, layer):
        o = jnp.einsum(
            "bnsh,nhd->bsd", o.astype(cdt), layer["out_w"].astype(cdt)
        ) + layer["out_b"].astype(cdt)
        x = x + _dropout(o, config.dropout, r1)

        h = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"]).astype(cdt)
        aux = jnp.zeros((), jnp.float32)
        if config.moe_experts:
            from ray_tpu.models.moe import moe_mlp

            moe = layer["moe"]
            h, aux = moe_mlp(
                h,
                moe["router_w"], moe["fc_w"], moe["fc_b"],
                moe["proj_w"], moe["proj_b"],
                capacity_factor=config.moe_capacity_factor,
            )
        else:
            h = jnp.einsum("bsd,df->bsf", h, layer["fc_w"].astype(cdt)) + layer["fc_b"].astype(cdt)
            h = jax.nn.gelu(h)
            h = jnp.einsum("bsf,fd->bsd", h, layer["proj_w"].astype(cdt)) + layer["proj_b"].astype(cdt)
        return x + _dropout(h, config.dropout, r2), aux

    if sub_remat:
        qkv_part = jax.checkpoint(qkv_part, prevent_cse=False)
        out_mlp_part = jax.checkpoint(out_mlp_part, prevent_cse=False)

    q, k, v = qkv_part(x, layer)
    from ray_tpu.models.stack import resolve_attention

    o = resolve_attention(q, k, v, config.attention, attention_fn)  # (B, nh, S, hd)
    return out_mlp_part(x, o, layer)


def forward(
    params: Dict[str, Any],
    tokens,  # (B, S) int32
    config: GPTConfig,
    attention_fn: Optional[Callable] = None,
    dropout_rng=None,
    mesh=None,
    num_microbatches: Optional[int] = None,
    return_aux: bool = False,
):
    """Returns logits (B, S, vocab) in float32 (with `return_aux`, a
    (logits, moe_aux_loss) pair). Pass dropout_rng to enable dropout
    (training); omit it for deterministic eval.

    With a mesh whose `pipeline` axis is >1, the layer stack runs as a GPipe
    microbatch pipeline (`parallel.pipeline`): each stage group holds
    n_layer/pipeline layers, activations ppermute between stages over ICI.
    Embedding and LM head stay outside the pipeline (replicated over the
    pipeline axis — they are a small fraction of the FLOPs)."""
    B, S = tokens.shape
    cdt = config.dtype
    x = params["wte"].astype(cdt)[tokens] + params["wpe"].astype(cdt)[:S][None]
    use_dropout = dropout_rng is not None and config.dropout > 0
    layers_rng = None
    if use_dropout:
        emb_rng, layers_rng = jax.random.split(dropout_rng)
        x = _dropout(x, config.dropout, emb_rng)

    remat_policy = (
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        if config.remat_policy == "dots"
        else None
    )
    save_attn = config.remat and config.remat_policy == "save_attn"

    def make_block_fn(first_layer, attn, mb_idx=None, seq_streams=()):
        def block_fn(x, xs):
            layer, idx = xs
            rng = None
            if use_dropout:
                rng = jax.random.fold_in(layers_rng, first_layer + idx)
                if mb_idx is not None:
                    # Independent dropout mask per microbatch under PP.
                    rng = jax.random.fold_in(rng, mb_idx)
            x, aux = _block(x, layer, config, attn, rng, sub_remat=save_attn)
            return x, aux

        if config.remat and not save_attn:
            block_fn = jax.checkpoint(block_fn, prevent_cse=False, policy=remat_policy)
        return block_fn

    from ray_tpu.models.stack import apply_stack

    x, moe_aux = apply_stack(
        params["blocks"],
        x,
        make_block_fn,
        n_layer=config.n_layer,
        attention_fn=attention_fn,
        mesh=mesh,
        num_microbatches=num_microbatches,
    )

    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    # Tied LM head: bf16 operands on the MXU, f32 accumulation — an f32×f32
    # matmul here would run at a fraction of MXU rate and this matmul is ~30%
    # of GPT-2-small's FLOPs.
    logits = jnp.einsum(
        "bsd,vd->bsv",
        x.astype(cdt),
        params["wte"].astype(cdt),
        preferred_element_type=jnp.float32,
    )
    if return_aux:
        return logits, moe_aux
    return logits


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, Any],  # {"tokens": (B, S+1)} or {"inputs","targets"}
    config: GPTConfig,
    attention_fn: Optional[Callable] = None,
    dropout_rng=None,
    mesh=None,
    num_microbatches: Optional[int] = None,
):
    """Causal LM cross entropy (mean over tokens)."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, moe_aux = forward(
        params, inputs, config, attention_fn, dropout_rng, mesh, num_microbatches,
        return_aux=True,
    )
    from ray_tpu.models.stack import causal_lm_loss

    loss = causal_lm_loss(logits, targets)
    if config.moe_experts:
        loss = loss + config.moe_aux_weight * moe_aux
    return loss
