"""HuggingFace checkpoint import: GPT-2 weights -> the zoo's pytree layout.

The reference's GPT-2 workloads fine-tune HF checkpoints through Ray Train
(`release/air_tests/air_benchmarks/` HF-Transformers benchmarks; BASELINE
config #4). This module is that on-ramp for the TPU build: load a
`transformers` GPT-2 (any size), convert to `models/gpt.py`'s stacked-layer
pytree, and continue training/fine-tuning under any mesh the zoo supports.

Conversion notes:
 - HF Conv1D stores weights (in, out) — already our einsum orientation.
 - c_attn packs q|k|v along the output dim: (d, 3d) -> (d, 3, nh, hd).
 - per-layer tensors stack on a leading `layers` dim (scan-over-layers).
 - the vocab pads up to a multiple of 128 (MXU tiling); padded embedding
   rows are zero and their logits sit at 0 — harmless for fine-tuning (they
   never appear as targets), slice `[:, :, :hf_vocab]` for exact HF logits.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.models.gpt import GPTConfig


def _pad_vocab(n: int, multiple: int = 128) -> int:
    return (n + multiple - 1) // multiple * multiple


def config_from_hf(hf_config, **overrides) -> GPTConfig:
    """GPTConfig matching a transformers GPT2Config (vocab padded for MXU).

    Raises on HF options this forward pass does not implement (non-gelu
    activations, non-default layer-norm eps) rather than silently diverging
    from the parity promise."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    # gpt.py computes jax.nn.gelu's tanh approximation; HF "gelu" is the
    # exact erf variant and would silently diverge from parity.
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported activation_function {act!r} (tanh-gelu only)")
    eps = float(getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if abs(eps - 1e-5) > 1e-9:
        raise ValueError(f"layer_norm_epsilon {eps} != 1e-5 (models/gpt.py hardcodes 1e-5)")
    kw = dict(
        vocab_size=_pad_vocab(hf_config.vocab_size),
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        d_model=hf_config.n_embd,
        d_ff=getattr(hf_config, "n_inner", None) or 0,  # 0 -> 4*d_model
        max_seq_len=hf_config.n_positions,
    )
    kw.update(overrides)
    return GPTConfig(**kw)


def load_hf_gpt2(model, **config_overrides) -> Tuple[GPTConfig, Dict[str, Any]]:
    """Convert a transformers GPT2LMHeadModel (or name) to (GPTConfig, params).

    Accepts a model instance or a checkpoint name for `from_pretrained`
    (instance is the offline-friendly path)."""
    if isinstance(model, str):
        from transformers import GPT2LMHeadModel

        model = GPT2LMHeadModel.from_pretrained(model)
    hf_cfg = model.config
    config = config_from_hf(hf_cfg, **config_overrides)
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    L, d = config.n_layer, config.d_model
    nh, hd, F = config.n_head, config.head_dim, config.ff_dim
    V_hf = hf_cfg.vocab_size
    pd = np.dtype(config.param_dtype)

    wte = np.zeros((config.vocab_size, d), pd)
    wte[:V_hf] = sd["transformer.wte.weight"]

    def stack(fmt, reshape=None):
        arrs = [sd[fmt.format(i)] for i in range(L)]
        out = np.stack([a.reshape(reshape) if reshape else a for a in arrs])
        return np.ascontiguousarray(out, pd)

    blocks = {
        "ln1_scale": stack("transformer.h.{}.ln_1.weight"),
        "ln1_bias": stack("transformer.h.{}.ln_1.bias"),
        "qkv_w": stack("transformer.h.{}.attn.c_attn.weight", (d, 3, nh, hd)),
        "qkv_b": stack("transformer.h.{}.attn.c_attn.bias", (3, nh, hd)),
        "out_w": stack("transformer.h.{}.attn.c_proj.weight", (nh, hd, d)),
        "out_b": stack("transformer.h.{}.attn.c_proj.bias"),
        "ln2_scale": stack("transformer.h.{}.ln_2.weight"),
        "ln2_bias": stack("transformer.h.{}.ln_2.bias"),
        "fc_w": stack("transformer.h.{}.mlp.c_fc.weight"),
        "fc_b": stack("transformer.h.{}.mlp.c_fc.bias"),
        "proj_w": stack("transformer.h.{}.mlp.c_proj.weight"),
        "proj_b": stack("transformer.h.{}.mlp.c_proj.bias"),
    }
    params = {
        "wte": wte,
        "wpe": np.ascontiguousarray(sd["transformer.wpe.weight"], pd),
        "blocks": blocks,
        "lnf_scale": np.ascontiguousarray(sd["transformer.ln_f.weight"], pd),
        "lnf_bias": np.ascontiguousarray(sd["transformer.ln_f.bias"], pd),
    }
    return config, params
