"""Llama-family transformer, TPU-first: RMSNorm, SwiGLU MLP, rotary position
embeddings, grouped-query attention, untied LM head.

Second model family of the zoo (same design rules as gpt.py): plain pytree
params with per-leaf logical axes, layers stacked + scanned (shared
`models/stack.py` scaffolding, so DP/FSDP/TP/PP/CP all compose exactly as for
GPT), bf16 matmuls with f32 norms/softmax/logits, pallas flash attention on
TPU with ring attention injectable for context parallelism.

The reference ships no model code; its user-facing analogue is the HF
workloads in `release/air_tests/air_benchmarks/` (e.g. Llama fine-tunes).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32  # < n_head = grouped-query attention
    d_model: int = 4096
    d_ff: int = 11008  # SwiGLU hidden dim (~8/3 * d, rounded to hardware-friendly)
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "save_attn" (default) remats the projections/MLP but keeps attention
    # outside the remat region (no kernel recompute in backward, q/k/v/o/lse
    # saved); "dots" saves matmul outputs across the block remat boundary;
    # None recomputes the whole block.
    remat_policy: Optional[str] = "save_attn"
    attention: str = "auto"  # auto | flash | xla

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    @property
    def group_size(self) -> int:
        assert self.n_head % self.n_kv_head == 0
        return self.n_head // self.n_kv_head

    # ---- presets ----
    @classmethod
    def llama2_7b(cls, **kw):
        return cls(**kw)

    @classmethod
    def llama2_13b(cls, **kw):
        return cls(n_layer=40, n_head=40, n_kv_head=40, d_model=5120, d_ff=13824, **kw)

    @classmethod
    def llama3_8b(cls, **kw):
        return cls(
            vocab_size=128256, n_layer=32, n_head=32, n_kv_head=8,
            d_model=4096, d_ff=14336, max_seq_len=8192, rope_theta=500000.0, **kw
        )

    @classmethod
    def nano(cls, **kw):
        """Tiny GQA config for CPU tests (2 kv heads for 4 q heads)."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 128)
        return cls(n_layer=2, n_head=4, n_kv_head=2, d_model=64, d_ff=128, **kw)


def num_params(config: LlamaConfig) -> int:
    d, L, V, F = config.d_model, config.n_layer, config.vocab_size, config.d_ff
    kvd = config.n_kv_head * config.head_dim
    per_layer = (
        d * d            # wq
        + 2 * d * kvd    # wk, wv
        + d * d          # wo
        + 2 * d * F      # w_gate, w_up
        + F * d          # w_down
        + 2 * d          # 2 rmsnorm scales
    )
    return 2 * V * d + L * per_layer + d  # embed + untied head + final norm


def train_flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    attn = 12 * config.n_layer * config.d_model * seq_len
    return 6.0 * num_params(config) + attn


# --------------------------------------------------------------------------- init
def init_params(config: LlamaConfig, key) -> Dict[str, Any]:
    d, L, V, F = config.d_model, config.n_layer, config.vocab_size, config.d_ff
    nh, nkv, hd = config.n_head, config.n_kv_head, config.head_dim
    k = iter(jax.random.split(key, 16))
    std = 0.02
    out_std = std / math.sqrt(2 * L)
    pd = config.param_dtype

    def norm(key, shape, s):
        return (jax.random.normal(key, shape) * s).astype(pd)

    return {
        "embed": norm(next(k), (V, d), std),
        "blocks": {
            "attn_norm": jnp.ones((L, d), pd),
            "wq": norm(next(k), (L, d, nh, hd), std),
            "wk": norm(next(k), (L, d, nkv, hd), std),
            "wv": norm(next(k), (L, d, nkv, hd), std),
            "wo": norm(next(k), (L, nh, hd, d), out_std),
            "mlp_norm": jnp.ones((L, d), pd),
            "w_gate": norm(next(k), (L, d, F), std),
            "w_up": norm(next(k), (L, d, F), std),
            "w_down": norm(next(k), (L, F, d), out_std),
        },
        "final_norm": jnp.ones((d,), pd),
        "lm_head": norm(next(k), (V, d), std),
    }


def param_logical_axes(config: LlamaConfig) -> Dict[str, Any]:
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads", None),
            "wk": ("layers", "embed", "kv_heads", None),
            "wv": ("layers", "embed", "kv_heads", None),
            "wo": ("layers", "heads", None, "embed"),
            "mlp_norm": ("layers", None),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": (None,),
        "lm_head": ("vocab", "embed"),
    }


# --------------------------------------------------------------------------- forward
def _rms_norm(x, scale, eps):
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return xf * rms * scale


def rope_tables(seq_len: int, head_dim: int, theta: float):
    """Precomputed (S, head_dim/2) cos/sin tables with GLOBAL positions —
    computed once per forward and passed through the stack as sequence
    streams, so context-parallel shards rotate with their true positions (a
    locally-indexed arange inside the block would restart every CP shard at
    position 0) and the tables aren't rebuilt per layer under remat."""
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(seq_len, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def _rope(x, cos, sin):
    """Apply rotary embeddings. x: (B, H, S_local, hd); cos/sin: (S_local, hd/2)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rx1 = x1 * cos - x2 * sin
    rx2 = x2 * cos + x1 * sin
    return jnp.concatenate([rx1, rx2], axis=-1).astype(x.dtype)




def _block(x, layer, config: LlamaConfig, attention_fn, cos, sin, sub_remat=False):
    """One Llama block. x: (B, S, D). Returns (x, aux=0).

    With sub_remat ("save_attn" policy), the qkv/rope and wo/MLP halves are
    individually remat'ed while attention between them is not — same policy
    as gpt._block."""
    cdt = config.dtype
    g = config.group_size

    def qkv_part(x, layer):
        h = _rms_norm(x, layer["attn_norm"], config.norm_eps).astype(cdt)
        q = jnp.einsum("bsd,dnh->bnsh", h, layer["wq"].astype(cdt))
        k = jnp.einsum("bsd,dnh->bnsh", h, layer["wk"].astype(cdt))
        v = jnp.einsum("bsd,dnh->bnsh", h, layer["wv"].astype(cdt))
        q = _rope(q, cos, sin)
        k = _rope(k, cos, sin)
        if g > 1:
            # GQA: each kv head serves `group_size` query heads.
            k = jnp.repeat(k, g, axis=1)
            v = jnp.repeat(v, g, axis=1)
        return q, k, v

    def out_mlp_part(x, o, layer):
        o = jnp.einsum("bnsh,nhd->bsd", o.astype(cdt), layer["wo"].astype(cdt))
        x = x + o

        h = _rms_norm(x, layer["mlp_norm"], config.norm_eps).astype(cdt)
        gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(cdt))
        up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cdt))
        h = jax.nn.silu(gate) * up
        h = jnp.einsum("bsf,fd->bsd", h, layer["w_down"].astype(cdt))
        return x + h, jnp.zeros((), jnp.float32)

    if sub_remat:
        qkv_part = jax.checkpoint(qkv_part, prevent_cse=False)
        out_mlp_part = jax.checkpoint(out_mlp_part, prevent_cse=False)

    q, k, v = qkv_part(x, layer)
    from ray_tpu.models.stack import resolve_attention

    o = resolve_attention(q, k, v, config.attention, attention_fn)  # (B, nh, S, hd)
    return out_mlp_part(x, o, layer)


def forward(
    params: Dict[str, Any],
    tokens,  # (B, S) int32
    config: LlamaConfig,
    attention_fn: Optional[Callable] = None,
    dropout_rng=None,  # accepted for API parity; Llama pretraining uses none
    mesh=None,
    num_microbatches: Optional[int] = None,
    return_aux: bool = False,
):
    """Logits (B, S, vocab) f32; pipelines over the `pipeline` mesh axis like
    GPT (shared stack scaffolding)."""
    del dropout_rng
    cdt = config.dtype
    S = tokens.shape[1]
    x = params["embed"].astype(cdt)[tokens]
    cos, sin = rope_tables(S, config.head_dim, config.rope_theta)

    remat_cfg = config.remat
    policy_name = getattr(config, "remat_policy", None)
    save_attn = remat_cfg and policy_name == "save_attn"
    remat_policy = (
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        if policy_name == "dots"
        else None
    )

    def make_block_fn(first_layer, attn, mb_idx=None, seq_streams=()):
        del first_layer, mb_idx  # no per-layer RNG (no dropout)
        cos_s, sin_s = seq_streams  # context-sharded slices under PPxCP

        def block_fn(x, xs):
            layer, _idx = xs
            return _block(x, layer, config, attn, cos_s, sin_s, sub_remat=save_attn)

        if remat_cfg and not save_attn:
            block_fn = jax.checkpoint(block_fn, prevent_cse=False, policy=remat_policy)
        return block_fn

    from ray_tpu.models.stack import apply_stack

    x, aux = apply_stack(
        params["blocks"],
        x,
        make_block_fn,
        n_layer=config.n_layer,
        attention_fn=attention_fn,
        mesh=mesh,
        num_microbatches=num_microbatches,
        seq_streams=(cos, sin),
    )

    x = _rms_norm(x, params["final_norm"], config.norm_eps)
    logits = jnp.einsum(
        "bsd,vd->bsv",
        x.astype(cdt),
        params["lm_head"].astype(cdt),
        preferred_element_type=jnp.float32,
    )
    if return_aux:
        return logits, aux
    return logits


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, Any],
    config: LlamaConfig,
    attention_fn: Optional[Callable] = None,
    dropout_rng=None,
    mesh=None,
    num_microbatches: Optional[int] = None,
):
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(
        params, inputs, config, attention_fn, dropout_rng, mesh, num_microbatches
    )
    from ray_tpu.models.stack import causal_lm_loss

    return causal_lm_loss(logits, targets)
