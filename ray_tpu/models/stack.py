"""Shared transformer-stack scaffolding: scan-over-layers with remat, and the
pipeline-parallel path — one implementation for every model family (GPT,
Llama, ...), so parallelism semantics cannot drift between models.

A model supplies `block_fn(x, (layer_params, idx)) -> (x, aux)`; this module
handles: lax.scan over stacked layer params, jax.checkpoint remat, and — when
the mesh has pipeline > 1 — the GPipe microbatch schedule with optional
in-region ring attention (parallel/pipeline.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def apply_stack(
    blocks,  # stacked per-layer params, leading dim n_layer
    x,  # (B, S, D)
    make_block_fn: Callable,  # (first_layer, attention_fn, mb_idx, seq_streams) -> block_fn
    *,
    n_layer: int,
    attention_fn: Optional[Callable],
    mesh=None,
    num_microbatches: Optional[int] = None,
    seq_streams: tuple = (),
) -> Tuple[Any, Any]:
    """Returns (activations, aux_sum). `make_block_fn` mirrors the model's
    per-block computation (dropout RNG handling included) and must already
    wrap remat if the config asks for it. `seq_streams` are per-position
    arrays (leading dim S, e.g. RoPE cos/sin tables) that shard with the
    sequence under context parallelism — inside the pipeline's manual region
    each rank receives its own slice, so global positions stay correct."""
    B = x.shape[0]
    n_pipeline = int(mesh.shape.get("pipeline", 1)) if mesh is not None else 1
    if n_pipeline > 1:
        from ray_tpu.parallel.pipeline import pipeline_apply, to_stages

        # Combining PP with CP: the pipeline region is manual over `pipeline`,
        # so context parallelism joins the same region with the in-region ring
        # attention (a nested full shard_map can't reopen a mesh axis).
        n_context = int(mesh.shape.get("context", 1))
        context_manual = n_context > 1
        inner_attn = attention_fn
        if context_manual:
            import functools

            from ray_tpu.parallel.ring_attention import ring_attention

            inner_attn = functools.partial(ring_attention, axis_name="context")

        def stack_fn(stage_local, xm, first_layer, mb_idx, streams):
            n_local = n_layer // n_pipeline
            xm, auxs = jax.lax.scan(
                make_block_fn(first_layer, inner_attn, mb_idx, streams),
                xm,
                (stage_local, jnp.arange(n_local)),
            )
            return xm, jnp.sum(auxs)

        M = num_microbatches or (2 * n_pipeline if B % (2 * n_pipeline) == 0 else n_pipeline)
        return pipeline_apply(
            mesh, to_stages(blocks, n_pipeline), x, stack_fn, M,
            context_manual=context_manual,
            seq_streams=seq_streams,
        )
    x, auxs = jax.lax.scan(
        make_block_fn(0, attention_fn, None, seq_streams),
        x,
        (blocks, jnp.arange(n_layer)),
    )
    return x, jnp.sum(auxs)


def resolve_attention(q, k, v, attention_mode: str, attention_fn: Optional[Callable]):
    """One attention-backend dispatch for every model family: caller-injected
    fn (ring/Ulysses wrappers) wins, else pallas flash on TPU / plain XLA."""
    if attention_fn is not None:
        return attention_fn(q, k, v)
    from ray_tpu.ops.flash_attention import flash_attention, xla_attention

    mode = attention_mode
    if mode == "auto":
        mode = "flash" if jax.default_backend() == "tpu" else "xla"
    if mode == "flash":
        return flash_attention(q, k, v, causal=True)
    return xla_attention(q, k, v, causal=True)


def causal_lm_loss(logits, targets):
    """Fused cross entropy: logsumexp - logit[target], one reduction over V
    instead of materializing the (B, S, V) log-softmax (saves ~2x V-sized HBM
    traffic)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    at_target = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return (lse - at_target).mean()
