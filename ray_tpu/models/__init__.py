from ray_tpu.models.gpt import (
    GPTConfig,
    forward,
    init_params,
    loss_fn,
    num_params,
    param_logical_axes,
    train_flops_per_token,
)
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.models.resnet import ResNetConfig
from ray_tpu.models.training import (
    TrainState,
    create_train_state,
    default_optimizer,
    make_train_step,
    param_shardings,
    shard_batch,
)

__all__ = [
    "GPTConfig",
    "LlamaConfig",
    "ResNetConfig",
    "TrainState",
    "create_train_state",
    "default_optimizer",
    "forward",
    "init_params",
    "loss_fn",
    "make_train_step",
    "num_params",
    "param_logical_axes",
    "param_shardings",
    "shard_batch",
    "train_flops_per_token",
]
