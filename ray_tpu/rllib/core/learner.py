"""JaxLearner: gradient updates as one jitted SPMD program.

Reference: `rllib/core/learner/learner.py:100` (`compute_gradients:409`,
`update:773`) and `torch_learner.py:143-194` (DDP wrap). The TPU redesign:
`update` is a single jitted function with donated state; when a mesh is
given, the batch shards over the data axis and XLA inserts the gradient
all-reduce over ICI — the learner never sees a collective call.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule


class JaxLearner:
    def __init__(
        self,
        module: RLModule,
        loss_fn: Callable,  # (module, params, batch) -> (loss, aux_dict)
        optimizer=None,
        learning_rate: float = 3e-4,
        mesh=None,
        seed: int = 0,
        extra_update_fn: Optional[Callable] = None,
    ):
        import jax
        import optax

        import inspect

        self.module = module
        self._loss_fn = loss_fn
        self.optimizer = optimizer or optax.adam(learning_rate)
        self.mesh = mesh
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        # Replicated auxiliary state the loss may consume (e.g. DQN's target
        # network params): loss_fn(module, params, batch, extra). It rides as
        # a jit argument with replicated sharding — never through the batch,
        # which shards over data and slices per remote learner.
        self.extra: Any = None
        # Optional pure (new_params, extra) -> new_extra, applied INSIDE the
        # jitted step (e.g. SAC's polyak target-network blend) — extra never
        # round-trips to the host between updates.
        self._extra_update_fn = extra_update_fn
        self._loss_wants_extra = len(inspect.signature(loss_fn).parameters) >= 4
        self._update = self._build_update()

    def _build_update(self):
        import jax
        import optax

        module, loss_fn, optimizer = self.module, self._loss_fn, self.optimizer
        wants_extra = self._loss_wants_extra
        extra_update_fn = self._extra_update_fn

        def step(params, opt_state, extra, batch):
            def loss_of(p):
                if wants_extra:
                    return loss_fn(module, p, batch, extra)
                return loss_fn(module, p, batch)

            (loss, aux), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            aux = dict(aux)
            aux["total_loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            if extra_update_fn is not None:
                extra = extra_update_fn(new_params, extra)
            return new_params, new_opt, extra, aux

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            data = NamedSharding(self.mesh, P("data"))
            return jax.jit(
                step,
                in_shardings=(repl, repl, repl, data),
                out_shardings=(repl, repl, repl, repl),
                donate_argnums=(0, 1, 2),
            )
        return jax.jit(step, donate_argnums=(0, 1, 2))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """One SGD step on a host batch. Scalar aux entries come back as
        floats; vector aux (e.g. DQN's per-sample `td_abs` for prioritized
        replay) comes back as numpy arrays — computed inside the same jitted
        step, so consumers never pay a second forward."""
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self.mesh, P("data"))
            batch = {k: jax.device_put(v, sharding) for k, v in batch.items()}
        self.params, self.opt_state, self.extra, aux = self._update(
            self.params, self.opt_state, self.extra, batch
        )
        out: Dict[str, Any] = {}
        for k, v in aux.items():
            arr = np.asarray(v)
            out[k] = arr if arr.ndim else float(arr)
        return out

    def set_extra(self, extra: Any) -> None:
        """Swap the replicated auxiliary state (e.g. a synced target network)."""
        self.extra = extra

    # ------------------------------------------------------------- state sync
    def get_weights(self) -> Any:
        import jax

        return jax.tree.map(lambda x: np.asarray(x), self.params)

    def set_weights(self, weights: Any) -> None:
        import jax

        self.params = jax.tree.map(lambda x: x, weights)
        # Note: opt_state is NOT reset; weights land mid-trajectory (PBT etc.)

    def state(self) -> Dict[str, Any]:
        import jax

        return {
            "params": self.get_weights(),
            "opt_state": jax.tree.map(lambda x: np.asarray(x), self.opt_state),
        }

    def load_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
