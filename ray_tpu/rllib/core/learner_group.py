"""LearnerGroup: local learner or a gang of remote learner actors.

Reference: `rllib/core/learner/learner_group.py:48-51` — "local or N remote
learners". Remote mode shards each update batch across learner actors; grad
sync is all-or-nothing weight averaging after each round (equivalent to
gradient averaging for equal shard sizes under the same optimizer state
trajectory — each learner applies the SAME averaged update because weights
are re-broadcast every round).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import RLModule


class _RemoteLearner:
    """Actor wrapping one JaxLearner (one host / one chip set)."""

    def __init__(self, module, loss_fn, learning_rate: float, seed: int,
                 optimizer=None, extra_update_fn=None):
        self.learner = JaxLearner(
            module, loss_fn, learning_rate=learning_rate, seed=seed,
            optimizer=optimizer, extra_update_fn=extra_update_fn,
        )

    def get_extra(self):
        return self.learner.extra

    def update(self, batch):
        return self.learner.update(batch)

    def set_extra(self, extra):
        self.learner.set_extra(extra)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)

    def state(self):
        return self.learner.state()

    def load_state(self, s):
        self.learner.load_state(s)


class LearnerGroup:
    def __init__(
        self,
        module: RLModule,
        loss_fn: Callable,
        *,
        num_learners: int = 0,
        learning_rate: float = 3e-4,
        mesh=None,
        optimizer=None,
        seed: int = 0,
        extra_update_fn=None,
    ):
        self._num = num_learners
        self._has_extra_update = extra_update_fn is not None
        if num_learners == 0:
            self._local = JaxLearner(
                module,
                loss_fn,
                learning_rate=learning_rate,
                mesh=mesh,
                optimizer=optimizer,
                seed=seed,
                extra_update_fn=extra_update_fn,
            )
            self._remote: List = []
        else:
            import ray_tpu

            self._local = None
            cls = ray_tpu.remote(_RemoteLearner)
            self._remote = [
                cls.options(num_cpus=1).remote(
                    module, loss_fn, learning_rate, seed, optimizer, extra_update_fn
                )
                for _ in range(num_learners)
            ]

    @property
    def is_local(self) -> bool:
        return self._local is not None

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch)
        import ray_tpu

        n = len(self._remote)
        size = len(next(iter(batch.values())))
        per = size // n
        shards = [
            {k: v[i * per:(i + 1) * per] for k, v in batch.items()} for i in range(n)
        ]
        metrics = ray_tpu.get(
            [lr.update.remote(s) for lr, s in zip(self._remote, shards)]
        )
        # Weight-average sync: every learner ends the round with identical
        # weights (the DDP-equivalence described in the module docstring).
        weights = ray_tpu.get([lr.get_weights.remote() for lr in self._remote])
        import jax

        avg = jax.tree.map(lambda *xs: np.mean(np.stack(xs), axis=0), *weights)
        ray_tpu.get([lr.set_weights.remote(avg) for lr in self._remote])
        if self._has_extra_update:
            # extra evolves INSIDE each learner's jitted step (e.g. SAC's
            # polyak targets blending toward that learner's pre-average
            # shard weights): resync it the same way as the weights, or the
            # per-learner copies drift apart round over round.
            extras = ray_tpu.get([lr.get_extra.remote() for lr in self._remote])
            if extras[0] is not None:
                avg_extra = jax.tree.map(
                    lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0),
                    *extras,
                )
                ray_tpu.get([lr.set_extra.remote(avg_extra) for lr in self._remote])
        out: Dict[str, Any] = {}
        for k in metrics[0]:
            if np.ndim(metrics[0][k]):
                # Vector aux (per-sample TD errors): shards sliced the batch
                # in order, so concatenation restores per-sample order
                # (covering the first n*per rows; the remainder was never
                # trained this round).
                out[k] = np.concatenate([np.asarray(m[k]) for m in metrics])
            else:
                out[k] = float(np.mean([m[k] for m in metrics]))
        return out

    def set_extra(self, extra) -> None:
        """Push replicated auxiliary loss state (e.g. DQN target params) to
        every learner — it must never ride the (data-sharded, sliced) batch."""
        if self._local is not None:
            self._local.set_extra(extra)
        else:
            import ray_tpu

            ray_tpu.get([lr.set_extra.remote(extra) for lr in self._remote])

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._remote[0].get_weights.remote())

    def get_extra(self):
        """Current replicated auxiliary state (post extra_update_fn blends)."""
        if self._local is not None:
            return self._local.extra
        import ray_tpu

        return ray_tpu.get(self._remote[0].get_extra.remote())

    def set_weights(self, w) -> None:
        if self._local is not None:
            self._local.set_weights(w)
        else:
            import ray_tpu

            ray_tpu.get([lr.set_weights.remote(w) for lr in self._remote])

    def state(self):
        if self._local is not None:
            return self._local.state()
        import ray_tpu

        return ray_tpu.get(self._remote[0].state.remote())

    def load_state(self, s) -> None:
        if self._local is not None:
            self._local.load_state(s)
        else:
            import ray_tpu

            ray_tpu.get([lr.load_state.remote(s) for lr in self._remote])
