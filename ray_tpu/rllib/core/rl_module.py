"""RLModule: the neural-net interface of the new RLlib stack, as jax pytrees.

Reference: `rllib/core/rl_module/rl_module.py` — a module exposes
forward_exploration / forward_inference / forward_train. Here a module is a
pair (init_params, pure apply fns) over jax pytrees so the learner can jit,
grad, and shard it freely; `MLPModule` is the default policy+value net
(the analogue of `rllib/models/jax/fcnet.py`, the reference's only jax net).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import numpy as np


class RLModule:
    """Interface: subclasses define init(key) -> params and pure forwards."""

    def init(self, key) -> Any:
        raise NotImplementedError

    def forward(self, params, obs):
        """Returns (action_logits, value_estimate)."""
        raise NotImplementedError

    def action_dist(self, params, obs, key, explore: bool = True):
        """Sample actions + logp under the current policy (jit-safe).

        Returns (action, logp, value, logits); the behavior logits ride along
        so PPO can compute the true KL(prev || curr) the way the reference does
        with stored ACTION_DIST_INPUTS (`ppo_torch_policy.py` loss).
        """
        import jax
        import jax.numpy as jnp

        logits, value = self.forward(params, obs)
        if explore:
            action = jax.random.categorical(key, logits, axis=-1)
        else:
            action = jnp.argmax(logits, axis=-1)
        logp = jax.nn.log_softmax(logits)
        act_logp = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
        return action, act_logp, value, logits


class QMLPModule(RLModule):
    """Single-tower Q-network MLP for value-based algorithms: forward returns
    per-action Q-values (logits slot) + max-Q (value slot); exploration is
    epsilon-greedy with epsilon passed as a traced scalar (the runner jits
    once and decays epsilon without recompiling). No value tower — every
    weight here is read on the Q path (checkpoints, target copies, and weight
    syncs stay half the size of the two-tower policy module)."""

    def __init__(self, obs_dim: int, num_actions: int, hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        sizes = (self.obs_dim, *self.hiddens, self.num_actions)
        layers = []
        for m, n in zip(sizes[:-1], sizes[1:]):
            key, sub = jax.random.split(key)
            scale = jnp.sqrt(2.0 / m)
            layers.append(
                {
                    "w": jax.random.normal(sub, (m, n), jnp.float32) * scale,
                    "b": jnp.zeros((n,), jnp.float32),
                }
            )
        return {"q": layers}

    def forward(self, params, obs):
        import jax.numpy as jnp

        x = obs
        layers = params["q"]
        for i, lyr in enumerate(layers):
            x = x @ lyr["w"] + lyr["b"]
            if i < len(layers) - 1:
                x = jnp.tanh(x)
        return x, x.max(axis=-1)

    def epsilon_greedy(self, params, obs, key, explore: bool, epsilon):
        import jax
        import jax.numpy as jnp

        q, value = self.forward(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        if explore:
            k1, k2 = jax.random.split(key)
            random_a = jax.random.randint(k1, greedy.shape, 0, q.shape[-1])
            u = jax.random.uniform(k2, greedy.shape)
            action = jnp.where(u < epsilon, random_a, greedy)
        else:
            action = greedy
        # logp slot unused for value-based policies; q rides the logits slot.
        return action, jnp.zeros(greedy.shape, jnp.float32), value, q


class MLPModule(RLModule):
    """Policy + value MLP with shared-nothing towers (categorical actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        def tower(key, sizes):
            layers = []
            for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
                key, sub = jax.random.split(key)
                scale = jnp.sqrt(2.0 / m)
                layers.append(
                    {
                        "w": jax.random.normal(sub, (m, n), jnp.float32) * scale,
                        "b": jnp.zeros((n,), jnp.float32),
                    }
                )
            return layers

        kp, kv = jax.random.split(key)
        pi_sizes = (self.obs_dim, *self.hiddens, self.num_actions)
        vf_sizes = (self.obs_dim, *self.hiddens, 1)
        params = {"pi": tower(kp, pi_sizes), "vf": tower(kv, vf_sizes)}
        # Near-zero policy head -> near-uniform initial policy (PPO-friendly).
        params["pi"][-1]["w"] = params["pi"][-1]["w"] * 0.01
        return params

    def forward(self, params, obs):
        import jax.numpy as jnp

        def run(layers, x, final_linear):
            for i, lyr in enumerate(layers):
                x = x @ lyr["w"] + lyr["b"]
                if i < len(layers) - 1 or not final_linear:
                    x = jnp.tanh(x)
            return x

        logits = run(params["pi"], obs, final_linear=True)
        value = run(params["vf"], obs, final_linear=True)[..., 0]
        return logits, value
