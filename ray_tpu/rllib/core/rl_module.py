"""RLModule: the neural-net interface of the new RLlib stack, as jax pytrees.

Reference: `rllib/core/rl_module/rl_module.py` — a module exposes
forward_exploration / forward_inference / forward_train. Here a module is a
pair (init_params, pure apply fns) over jax pytrees so the learner can jit,
grad, and shard it freely; `MLPModule` is the default policy+value net
(the analogue of `rllib/models/jax/fcnet.py`, the reference's only jax net).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import numpy as np


def mlp_init(key, sizes, final_scale: float = 1.0):
    """He-scaled MLP tower init shared by every module class: list of
    {"w", "b"} layer dicts; the last layer's weights scale by final_scale
    (e.g. 0.01 for a near-uniform initial policy)."""
    import jax
    import jax.numpy as jnp

    layers = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / m)
        if i == len(sizes) - 2:
            scale = scale * final_scale
        layers.append(
            {
                "w": jax.random.normal(sub, (m, n), jnp.float32) * scale,
                "b": jnp.zeros((n,), jnp.float32),
            }
        )
    return layers


_ACTIVATIONS = {}


def _activation(name: str):
    """Resolve an activation name to a jax fn (cached; import-light)."""
    fn = _ACTIVATIONS.get(name)
    if fn is None:
        import jax
        import jax.numpy as jnp

        table = {
            "tanh": jnp.tanh,
            "relu": jax.nn.relu,
            "silu": jax.nn.silu,
            "swish": jax.nn.silu,
            "elu": jax.nn.elu,
            "gelu": jax.nn.gelu,
        }
        if name not in table:
            raise ValueError(
                f"unknown activation {name!r}; one of {sorted(table)}"
            )
        fn = _ACTIVATIONS[name] = table[name]
    return fn


def mlp_forward(layers, x, activation: str = "tanh"):
    """Run an mlp_init tower: `activation` between layers, linear final."""
    act = _activation(activation)
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = act(x)
    return x


class RLModule:
    """Interface: subclasses define init(key) -> params and pure forwards."""

    def init(self, key) -> Any:
        raise NotImplementedError

    def forward(self, params, obs):
        """Returns (action_logits, value_estimate)."""
        raise NotImplementedError

    def action_dist(self, params, obs, key, explore: bool = True):
        """Sample actions + logp under the current policy (jit-safe).

        Returns (action, logp, value, logits); the behavior logits ride along
        so PPO can compute the true KL(prev || curr) the way the reference does
        with stored ACTION_DIST_INPUTS (`ppo_torch_policy.py` loss).
        """
        import jax
        import jax.numpy as jnp

        logits, value = self.forward(params, obs)
        if explore:
            action = jax.random.categorical(key, logits, axis=-1)
        else:
            action = jnp.argmax(logits, axis=-1)
        logp = jax.nn.log_softmax(logits)
        act_logp = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
        return action, act_logp, value, logits


class QValueModule(RLModule):
    """Base for Q-value modules: subclasses define forward -> (q, max_q) and
    inherit the ONE epsilon-greedy implementation. The runner detects
    value-based modules by the presence of `epsilon_greedy`, so this method
    must live here and NOT on RLModule (policy modules would otherwise be
    misrouted onto the epsilon path)."""

    # Replay-trained: the runner skips logp/value/dist buffers entirely.
    off_policy = True

    def epsilon_greedy(self, params, obs, key, explore: bool, epsilon):
        import jax
        import jax.numpy as jnp

        q, value = self.forward(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        if explore:
            k1, k2 = jax.random.split(key)
            random_a = jax.random.randint(k1, greedy.shape, 0, q.shape[-1])
            u = jax.random.uniform(k2, greedy.shape)
            action = jnp.where(u < epsilon, random_a, greedy)
        else:
            action = greedy
        # logp slot unused for value-based policies; q rides the logits slot.
        return action, jnp.zeros(greedy.shape, jnp.float32), value, q


class QMLPModule(QValueModule):
    """Single-tower Q-network MLP for value-based algorithms: forward returns
    per-action Q-values (logits slot) + max-Q (value slot); exploration is
    epsilon-greedy with epsilon passed as a traced scalar (the runner jits
    once and decays epsilon without recompiling). No value tower — every
    weight here is read on the Q path (checkpoints, target copies, and weight
    syncs stay half the size of the two-tower policy module)."""

    def __init__(self, obs_dim: int, num_actions: int, hiddens: Sequence[int] = (64, 64),
                 activation: str = "tanh"):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)
        self.activation = activation

    def init(self, key):
        return {"q": mlp_init(key, (self.obs_dim, *self.hiddens, self.num_actions))}

    def forward(self, params, obs):
        q = mlp_forward(params["q"], obs, self.activation)
        return q, q.max(axis=-1)


class MLPModule(RLModule):
    """Policy + value MLP with shared-nothing towers (categorical actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64), activation: str = "tanh"):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)
        self.activation = activation

    def init(self, key):
        import jax

        kp, kv = jax.random.split(key)
        return {
            # Near-zero policy head -> near-uniform initial policy.
            "pi": mlp_init(kp, (self.obs_dim, *self.hiddens, self.num_actions), final_scale=0.01),
            "vf": mlp_init(kv, (self.obs_dim, *self.hiddens, 1)),
        }

    def forward(self, params, obs):
        logits = mlp_forward(params["pi"], obs, self.activation)
        value = mlp_forward(params["vf"], obs, self.activation)[..., 0]
        return logits, value


class SquashedGaussianModule(RLModule):
    """Continuous-control actor-critic: tanh-squashed Gaussian policy + twin
    Q towers (SAC's module). Actions map to the Box bounds via an affine of
    tanh(u); log-probs carry the tanh + affine Jacobian corrections.

    Reference: `rllib/algorithms/sac/sac_torch_model.py` (policy net emitting
    (mean, log_std), twin Q-nets over concat(obs, act)); here the whole thing
    is one pytree {"pi", "q1", "q2", "log_alpha"} so JaxLearner can jit/grad
    the combined SAC objective in a single SPMD step."""

    off_policy = True
    LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0

    def __init__(self, obs_dim: int, act_low, act_high,
                 hiddens: Sequence[int] = (256, 256), activation: str = "tanh"):
        self.obs_dim = obs_dim
        self.act_low = np.asarray(act_low, np.float32)
        self.act_high = np.asarray(act_high, np.float32)
        self.act_dim = int(self.act_low.size)
        self.center = (self.act_high + self.act_low) / 2.0
        self.scale = (self.act_high - self.act_low) / 2.0
        self.hiddens = tuple(hiddens)
        self.activation = activation

    def init(self, key):
        import jax
        import jax.numpy as jnp

        kp, k1, k2 = jax.random.split(key, 3)
        return {
            "pi": mlp_init(kp, (self.obs_dim, *self.hiddens, 2 * self.act_dim)),
            "q1": mlp_init(k1, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "q2": mlp_init(k2, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "log_alpha": jnp.zeros((), jnp.float32),
        }

    # ------------------------------------------------------------ policy math
    def dist_params(self, params, obs):
        import jax.numpy as jnp

        out = mlp_forward(params["pi"], obs, self.activation)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mean, log_std

    def sample(self, params, obs, noise):
        """Reparameterized squashed sample from pre-drawn standard normals.
        Returns (action_env_scale, logp)."""
        import jax.numpy as jnp

        mean, log_std = self.dist_params(params, obs)
        std = jnp.exp(log_std)
        u = mean + std * noise
        a_raw = jnp.tanh(u)
        # N(u; mean, std) log-density, then tanh + affine Jacobians.
        logp = jnp.sum(
            -0.5 * jnp.square(noise) - log_std - 0.5 * jnp.log(2.0 * jnp.pi),
            axis=-1,
        )
        logp = logp - jnp.sum(jnp.log(1.0 - jnp.square(a_raw) + 1e-6), axis=-1)
        logp = logp - float(np.sum(np.log(self.scale)))
        return self.center + self.scale * a_raw, logp

    def q_values(self, q_params, obs, action_env):
        """Q(s, a) for one tower; actions normalize back to (-1, 1) so tower
        inputs stay O(1) regardless of the env's bounds."""
        import jax.numpy as jnp

        a = (action_env - self.center) / self.scale
        x = jnp.concatenate([obs, a], axis=-1)
        return mlp_forward(q_params, x, self.activation)[..., 0]

    # ----------------------------------------------------------- runner hooks
    def forward(self, params, obs):
        """(dist params, Q(s, mean action)) — value slot for diagnostics."""
        import jax.numpy as jnp

        mean, log_std = self.dist_params(params, obs)
        a_env = self.center + self.scale * jnp.tanh(mean)
        return jnp.concatenate([mean, log_std], axis=-1), self.q_values(
            params["q1"], obs, a_env
        )

    def action_dist(self, params, obs, key, explore: bool = True):
        import jax
        import jax.numpy as jnp

        mean, log_std = self.dist_params(params, obs)
        if explore:
            noise = jax.random.normal(key, mean.shape)
        else:
            noise = jnp.zeros_like(mean)
        action, logp = self.sample(params, obs, noise)
        dist = jnp.concatenate([mean, log_std], axis=-1)
        value = self.q_values(params["q1"], obs, action)
        return action, logp, value, dist


class DeterministicContinuousModule(RLModule):
    """Deterministic continuous-control actor-critic: tanh policy mapped to
    the Box bounds + twin Q towers (TD3's module; DDPG uses one tower of it).

    Reference: `rllib/algorithms/ddpg/ddpg_torch_model.py` (deterministic
    policy net + twin Q-nets with `twin_q`). One pytree {"pi", "q1", "q2"}
    so the learner jits the combined TD3 objective; exploration is Gaussian
    noise on the env-scale action, clipped to bounds, with the noise scale
    fixed at construction (the reference's `exploration_config` sigma).
    """

    off_policy = True

    def __init__(self, obs_dim: int, act_low, act_high,
                 hiddens: Sequence[int] = (256, 256), activation: str = "tanh",
                 explore_noise: float = 0.1):
        self.obs_dim = obs_dim
        self.act_low = np.asarray(act_low, np.float32)
        self.act_high = np.asarray(act_high, np.float32)
        self.act_dim = int(self.act_low.size)
        self.center = (self.act_high + self.act_low) / 2.0
        self.scale = (self.act_high - self.act_low) / 2.0
        self.hiddens = tuple(hiddens)
        self.activation = activation
        self.explore_noise = float(explore_noise)

    def init(self, key):
        import jax

        kp, k1, k2 = jax.random.split(key, 3)
        return {
            "pi": mlp_init(kp, (self.obs_dim, *self.hiddens, self.act_dim)),
            "q1": mlp_init(k1, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
            "q2": mlp_init(k2, (self.obs_dim + self.act_dim, *self.hiddens, 1)),
        }

    def pi(self, params, obs):
        """Deterministic env-scale action."""
        import jax.numpy as jnp

        raw = mlp_forward(params["pi"], obs, self.activation)
        return self.center + self.scale * jnp.tanh(raw)

    def q_values(self, q_params, obs, action_env):
        import jax.numpy as jnp

        a = (action_env - self.center) / self.scale
        x = jnp.concatenate([obs, a], axis=-1)
        return mlp_forward(q_params, x, self.activation)[..., 0]

    def forward(self, params, obs):
        a = self.pi(params, obs)
        return a, self.q_values(params["q1"], obs, a)

    def action_dist(self, params, obs, key, explore: bool = True):
        import jax
        import jax.numpy as jnp

        a = self.pi(params, obs)
        if explore:
            noise = jax.random.normal(key, a.shape) * (
                self.explore_noise * self.scale
            )
            a = jnp.clip(a + noise, self.act_low, self.act_high)
        value = self.q_values(params["q1"], obs, a)
        # logp slot unused for deterministic policies; action rides the
        # logits slot for diagnostics.
        return a, jnp.zeros(a.shape[:-1], jnp.float32), value, a
