"""Distributional (C51) Q-network module with optional dueling heads.

Reference: `rllib/algorithms/dqn/dqn_torch_model.py` (`num_atoms > 1`
categorical distributional head, `dueling` value/advantage split — the
reference's Rainbow pieces are DQN config knobs, not a separate algorithm)
and Bellemare et al. 2017 (C51).

TPU-first shape: the module emits per-action atom LOGITS in one (B, A,
natoms) tensor from a shared trunk — the dueling combine (value + advantage
- mean advantage) happens in logit space inside the same jitted forward, and
scalar Q-values are the support-weighted softmax reduced on-device. The
categorical projection lives in the loss (`dqn.py make_c51_loss`), not here.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ray_tpu.rllib.core.rl_module import QValueModule, mlp_forward, mlp_init


class DuelingQMLPModule(QValueModule):
    """Scalar dueling Q-net (reference `dueling=True`, num_atoms=1):
    Q(s,a) = V(s) + A(s,a) - mean_a A(s,a), heads off a shared trunk."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64), activation: str = "tanh"):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)
        self.activation = activation

    def init(self, key):
        import jax

        kt, ka, kv = jax.random.split(key, 3)
        return {
            "trunk": mlp_init(kt, (self.obs_dim, *self.hiddens)),
            "adv": mlp_init(ka, (self.hiddens[-1], self.num_actions)),
            "val": mlp_init(kv, (self.hiddens[-1], 1)),
        }

    def forward(self, params, obs):
        from ray_tpu.rllib.core.rl_module import _activation

        h = _activation(self.activation)(
            mlp_forward(params["trunk"], obs, self.activation)
        )
        adv = mlp_forward(params["adv"], h, self.activation)
        val = mlp_forward(params["val"], h, self.activation)
        q = val + adv - adv.mean(axis=-1, keepdims=True)
        return q, q.max(axis=-1)


class DistributionalQModule(QValueModule):
    """C51 Q-net: trunk -> (dueling) atom-logit heads; Q = E_z[softmax]."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64), activation: str = "tanh",
                 num_atoms: int = 51, v_min: float = -10.0, v_max: float = 10.0,
                 dueling: bool = True):
        if num_atoms < 2:
            raise ValueError("num_atoms must be >= 2 (use QMLPModule for scalar Q)")
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)
        self.activation = activation
        self.num_atoms = int(num_atoms)
        self.v_min = float(v_min)
        self.v_max = float(v_max)
        self.dueling = bool(dueling)
        # Fixed support; a buffer, not a parameter.
        self.support = np.linspace(v_min, v_max, num_atoms).astype(np.float32)

    def init(self, key):
        import jax

        kt, ka, kv = jax.random.split(key, 3)
        trunk_sizes = (self.obs_dim, *self.hiddens)
        params = {
            "trunk": mlp_init(kt, trunk_sizes),
            "adv": mlp_init(
                ka, (self.hiddens[-1], self.num_actions * self.num_atoms)
            ),
        }
        if self.dueling:
            params["val"] = mlp_init(kv, (self.hiddens[-1], self.num_atoms))
        return params

    # -------------------------------------------------------------- forwards
    def _trunk(self, params, obs):
        act = mlp_forward(params["trunk"], obs, self.activation)
        # mlp_forward leaves the last layer linear; the trunk feeds heads, so
        # apply the nonlinearity it skipped.
        from ray_tpu.rllib.core.rl_module import _activation

        return _activation(self.activation)(act)

    def dist_logits(self, params, obs):
        """(B, A, natoms) atom logits; dueling combine in logit space."""
        import jax.numpy as jnp

        h = self._trunk(params, obs)
        adv = mlp_forward(params["adv"], h, self.activation).reshape(
            obs.shape[:-1] + (self.num_actions, self.num_atoms)
        )
        if not self.dueling:
            return adv
        val = mlp_forward(params["val"], h, self.activation)[..., None, :]
        return val + adv - adv.mean(axis=-2, keepdims=True)

    def dist_probs(self, params, obs):
        import jax

        return jax.nn.softmax(self.dist_logits(params, obs), axis=-1)

    def forward(self, params, obs):
        """Scalar Q-values (B, A) = support-weighted atom probabilities."""
        import jax.numpy as jnp

        probs = self.dist_probs(params, obs)
        q = jnp.sum(probs * jnp.asarray(self.support), axis=-1)
        return q, q.max(axis=-1)
