from ray_tpu.rllib.models.catalog import (
    MODEL_DEFAULTS,
    ModelCatalog,
    register_custom_module,
)

__all__ = ["MODEL_DEFAULTS", "ModelCatalog", "register_custom_module"]
