"""ModelCatalog: config-driven module construction.

Reference: `rllib/models/catalog.py:197` (`ModelCatalog.get_model_v2` — the
registry that turns a `model` config dict into a network for the algorithm's
needs). Here the catalog maps `config.model` onto the jax RLModule zoo:
`kind` names what the algorithm needs (policy+value, Q-net, squashed
Gaussian, deterministic continuous), the model dict supplies architecture
(`hiddens`/`fcnet_hiddens`, `activation`/`fcnet_activation`, `custom_module`).
Custom architectures plug in via `register_custom_module` + `custom_module`,
mirroring the reference's `ModelCatalog.register_custom_model`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

MODEL_DEFAULTS: Dict[str, Any] = {
    # Reference names (fcnet_*) and repo-native names are both accepted.
    "hiddens": (64, 64),
    "activation": "tanh",
    "custom_module": None,
    "custom_module_config": {},
}

_CUSTOM_MODULES: Dict[str, Callable] = {}


def register_custom_module(name: str, factory: Callable) -> None:
    """Register a module factory invoked as
    `factory(obs_dim, action_space, model_config)` when `config.model`
    contains `custom_module: name` (reference:
    `ModelCatalog.register_custom_model`)."""
    _CUSTOM_MODULES[name] = factory


def _hiddens(model_config: Dict[str, Any], default=(64, 64)):
    h = model_config.get("hiddens", model_config.get("fcnet_hiddens", default))
    return tuple(int(x) for x in h)


def _activation(model_config: Dict[str, Any]) -> str:
    return str(
        model_config.get(
            "activation", model_config.get("fcnet_activation", "tanh")
        )
    )


class ModelCatalog:
    """Stateless factory; all construction rides classmethods like the
    reference's."""

    @staticmethod
    def get_module(
        kind: str,
        obs_dim: int,
        action_space: Any,
        model_config: Dict[str, Any],
    ):
        """Build the RLModule for `kind`:

        - "pi_vf": policy + value towers over Discrete actions
        - "q": Q-network over Discrete actions
        - "squashed_gaussian": SAC-style stochastic continuous actor-critic
        - "deterministic_continuous": TD3/DDPG-style deterministic actor +
          twin critics

        `action_space` is a gymnasium space (Discrete or Box per kind);
        `model_config` is the algorithm's `config.model` dict.
        """
        from ray_tpu.rllib.core import rl_module as m

        custom = model_config.get("custom_module")
        if custom:
            if custom not in _CUSTOM_MODULES:
                raise ValueError(
                    f"custom_module {custom!r} is not registered "
                    "(register_custom_module first)"
                )
            return _CUSTOM_MODULES[custom](obs_dim, action_space, model_config)

        act = _activation(model_config)
        if kind == "pi_vf":
            return m.MLPModule(
                obs_dim, int(action_space.n),
                hiddens=_hiddens(model_config), activation=act,
            )
        if kind == "q":
            return m.QMLPModule(
                obs_dim, int(action_space.n),
                hiddens=_hiddens(model_config), activation=act,
            )
        if kind == "squashed_gaussian":
            return m.SquashedGaussianModule(
                obs_dim, action_space.low, action_space.high,
                hiddens=_hiddens(model_config, (256, 256)), activation=act,
            )
        if kind == "deterministic_continuous":
            return m.DeterministicContinuousModule(
                obs_dim, action_space.low, action_space.high,
                hiddens=_hiddens(model_config, (256, 256)), activation=act,
            )
        raise ValueError(f"unknown module kind {kind!r}")
