"""Replay buffers: uniform ring + proportional prioritized (sum-tree).

Reference: `rllib/utils/replay_buffers/replay_buffer.py` (uniform) and
`prioritized_replay_buffer.py` + `rllib/execution/segment_tree.py`
(proportional prioritization, Schaul et al. 2016). The reference's segment
tree is a Python object updated element-by-element; here the sum-tree is one
flat numpy array and sampling/updating are vectorized over the whole batch —
a level-by-level descent of shape (batch,) index arrays, O(log n) vector ops
per batch instead of O(batch * log n) Python iterations.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer over flat numpy transition columns."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._store: Dict[str, np.ndarray] = {}
        self._next = 0
        self.size = 0

    def _added_indices(self, n: int) -> np.ndarray:
        idx = (self._next + np.arange(n)) % self.capacity
        self._next = (self._next + n) % self.capacity
        self.size = min(self.size + n, self.capacity)
        return idx

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._store:
            for k, v in batch.items():
                self._store[k] = np.zeros((self.capacity,) + v.shape[1:], v.dtype)
        idx = self._added_indices(n)
        for k, v in batch.items():
            self._store[k][idx] = v

    def sample(self, batch_size: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay: P(i) ~ p_i^alpha, IS weights
    w_i = (N * P(i))^-beta / max_j w_j ride the sampled batch as
    `loss_weight` (the TD losses already multiply by that column) together
    with `batch_indexes` for `update_priorities`."""

    def __init__(self, capacity: int, alpha: float = 0.6):
        super().__init__(capacity)
        if alpha <= 0:
            raise ValueError("alpha must be > 0")
        self.alpha = float(alpha)
        # Leaf i of the sum-tree lives at _tree[_cap2 + i]; internal node k
        # holds the sum of its two children, root at _tree[1].
        self._cap2 = 1 << (capacity - 1).bit_length()
        self._depth = self._cap2.bit_length() - 1
        self._tree = np.zeros(2 * self._cap2, np.float64)
        self._max_priority = 1.0

    # ------------------------------------------------------------- tree ops
    def _set_priorities(self, idx: np.ndarray, prio: np.ndarray) -> None:
        """Vectorized leaf assign + path re-sum. Duplicate idx entries keep
        the LAST value (np fancy-assign semantics), then each affected path
        is recomputed bottom-up from child sums, so duplicates stay exact."""
        leaf = self._cap2 + idx
        self._tree[leaf] = prio
        parents = leaf // 2
        for _ in range(self._depth):
            parents = np.unique(parents)
            self._tree[parents] = self._tree[2 * parents] + self._tree[2 * parents + 1]
            parents //= 2

    def _sample_leaves(self, u: np.ndarray) -> np.ndarray:
        """Descend the tree with a batch of prefix-sum targets at once."""
        idx = np.ones(len(u), np.int64)
        u = u.astype(np.float64).copy()
        for _ in range(self._depth):
            left = 2 * idx
            lsum = self._tree[left]
            go_right = u >= lsum
            u -= np.where(go_right, lsum, 0.0)
            idx = left + go_right
        return idx - self._cap2

    # ------------------------------------------------------------ buffer API
    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._store:
            for k, v in batch.items():
                self._store[k] = np.zeros((self.capacity,) + v.shape[1:], v.dtype)
        idx = self._added_indices(n)
        for k, v in batch.items():
            self._store[k][idx] = v
        # New transitions get max priority so everything is seen at least
        # once before TD errors take over (reference: `add` -> max_priority).
        self._set_priorities(
            idx, np.full(n, self._max_priority**self.alpha, np.float64)
        )

    def sample(self, batch_size: int, rng: np.random.Generator,
               beta: float = 0.4) -> Dict[str, np.ndarray]:
        total = self._tree[1]
        if total <= 0 or self.size == 0:
            raise ValueError("cannot sample from an empty buffer")
        # Stratified draw: one uniform per equal-mass segment keeps sample
        # diversity high at small batch sizes.
        seg = total / batch_size
        u = (np.arange(batch_size) + rng.random(batch_size)) * seg
        idx = np.clip(self._sample_leaves(u), 0, self.size - 1)
        out = {k: v[idx] for k, v in self._store.items()}
        p = self._tree[self._cap2 + idx] / total
        weights = (self.size * np.maximum(p, 1e-12)) ** (-beta)
        weights = weights / weights.max()
        base = out.get("loss_weight")
        w = weights.astype(np.float32)
        out["loss_weight"] = w if base is None else base * w
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray) -> None:
        priorities = np.asarray(priorities, np.float64)
        if np.any(priorities < 0):
            raise ValueError("priorities must be >= 0")
        eps = 1e-6
        self._max_priority = max(self._max_priority, float(priorities.max(initial=0.0)))
        self._set_priorities(np.asarray(idx, np.int64), (priorities + eps) ** self.alpha)

    def stats(self) -> Dict[str, float]:
        return {
            "size": float(self.size),
            "max_priority": self._max_priority,
            "priority_total": float(self._tree[1]),
        }
