"""Exploration strategy library: pluggable, jit-safe action selection.

Reference: `rllib/utils/exploration/` — EpsilonGreedy (`epsilon_greedy.py`),
SoftQ (`soft_q.py`), StochasticSampling (`stochastic_sampling.py`), Random
(`random.py`), GaussianNoise (`gaussian_noise.py`), OrnsteinUhlenbeckNoise
(`ornstein_uhlenbeck_noise.py`), ParameterNoise (`parameter_noise.py`).

TPU-first shape: a strategy is a pair of pure functions — `actions(...)`
runs INSIDE the runner's single jitted forward with all annealable knobs
(epsilon, noise scale, OU state) passed as a traced pytree `state`, so
schedule decay and stateful noise never retrigger compilation; `schedule()`
is driver-side numpy that recomputes the annealed scalars from the global
env-step count and is pushed to runners with the weight sync. The reference
instead threads framework-conditional torch/tf ops through each policy's
action sampler; here the jit boundary forces the clean split.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class Exploration:
    """Interface. `actions` must be pure/jittable: state in, state out."""

    #: strategies that need per-env persistent arrays (OU noise) override.
    def initial_state(self, num_envs: int, act_shape: Tuple[int, ...]) -> Dict[str, Any]:
        return {}

    def schedule(self, env_steps: int) -> Dict[str, Any]:
        """Driver-side: annealed scalars for the current global step count.
        Merged into the runner's live state by `EnvRunner.set_exploration`."""
        return {}

    def on_weights(self, params, key):
        """Hook at weight-sync time (ParameterNoise perturbs here). Returns
        the params the ROLLOUT should use; learner params are untouched."""
        return params

    def actions(self, module, params, obs, key, explore: bool, state: Dict[str, Any]):
        """(action, logp, value, dist_inputs, new_state); jit-safe."""
        raise NotImplementedError


def _anneal(initial: float, final: float, steps: int, t: int) -> float:
    frac = min(1.0, t / max(1, steps))
    return float(initial + frac * (final - initial))


class EpsilonGreedy(Exploration):
    """Annealed epsilon-greedy over Q-values (reference:
    `rllib/utils/exploration/epsilon_greedy.py`)."""

    def __init__(self, initial_epsilon: float = 1.0, final_epsilon: float = 0.05,
                 epsilon_timesteps: int = 10_000):
        self.initial_epsilon = float(initial_epsilon)
        self.final_epsilon = float(final_epsilon)
        self.epsilon_timesteps = int(epsilon_timesteps)

    def initial_state(self, num_envs, act_shape):
        return {"epsilon": np.float32(self.initial_epsilon)}

    def schedule(self, env_steps):
        return {
            "epsilon": np.float32(
                _anneal(self.initial_epsilon, self.final_epsilon,
                        self.epsilon_timesteps, env_steps)
            )
        }

    def actions(self, module, params, obs, key, explore, state):
        import jax
        import jax.numpy as jnp

        if hasattr(module, "epsilon_greedy"):
            # Q modules carry the canonical implementation (QMLPModule);
            # delegating keeps one copy of the argmax/dither block.
            a, logp, v, d = module.epsilon_greedy(
                params, obs, key, explore, state["epsilon"]
            )
            return a, logp, v, d, state
        q, value = module.forward(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        if explore:
            k1, k2 = jax.random.split(key)
            random_a = jax.random.randint(k1, greedy.shape, 0, q.shape[-1])
            u = jax.random.uniform(k2, greedy.shape)
            action = jnp.where(u < state["epsilon"], random_a, greedy)
        else:
            action = greedy
        return action, jnp.zeros(greedy.shape, jnp.float32), value, q, state


class SoftQ(Exploration):
    """Boltzmann sampling from softmax(Q / temperature) (reference:
    `rllib/utils/exploration/soft_q.py`)."""

    def __init__(self, temperature: float = 1.0):
        self.temperature = float(temperature)

    def initial_state(self, num_envs, act_shape):
        return {"temperature": np.float32(self.temperature)}

    def actions(self, module, params, obs, key, explore, state):
        import jax
        import jax.numpy as jnp

        q, value = module.forward(params, obs)
        if explore:
            logits = q / jnp.maximum(state["temperature"], 1e-8)
            action = jax.random.categorical(key, logits, axis=-1)
        else:
            action = jnp.argmax(q, axis=-1)
        return action, jnp.zeros(action.shape, jnp.float32), value, q, state


class StochasticSampling(Exploration):
    """Sample the module's own action distribution (reference:
    `rllib/utils/exploration/stochastic_sampling.py` — the PG default)."""

    def actions(self, module, params, obs, key, explore, state):
        a, logp, v, d = module.action_dist(params, obs, key, explore)
        return a, logp, v, d, state


class Random(Exploration):
    """Uniform-random actions while exploring; greedy otherwise (reference:
    `rllib/utils/exploration/random.py` — pure-exploration warmup)."""

    def actions(self, module, params, obs, key, explore, state):
        import jax
        import jax.numpy as jnp

        if not explore:
            a, logp, v, d = module.action_dist(params, obs, key, False)
            return a, logp, v, d, state
        out, value = module.forward(params, obs)
        low = getattr(module, "act_low", None)
        if low is not None:  # continuous Box
            action = jax.random.uniform(
                key, obs.shape[:-1] + (module.act_dim,),
                minval=module.act_low, maxval=module.act_high,
            )
            return action, jnp.zeros(action.shape[:-1], jnp.float32), value, out, state
        action = jax.random.randint(key, out.shape[:-1], 0, out.shape[-1])
        return action, jnp.zeros(action.shape, jnp.float32), value, out, state


class GaussianNoise(Exploration):
    """Deterministic action + annealed additive Gaussian noise, clipped to
    bounds (reference: `rllib/utils/exploration/gaussian_noise.py` — the
    DDPG/TD3 default). `scale` anneals initial->final over scale_timesteps."""

    def __init__(self, stddev: float = 0.1, initial_scale: float = 1.0,
                 final_scale: float = 1.0, scale_timesteps: int = 10_000,
                 random_timesteps: int = 0):
        self.stddev = float(stddev)
        self.initial_scale = float(initial_scale)
        self.final_scale = float(final_scale)
        self.scale_timesteps = int(scale_timesteps)
        self.random_timesteps = int(random_timesteps)

    def initial_state(self, num_envs, act_shape):
        return {
            "scale": np.float32(self.initial_scale),
            # >0 while in the pure-random warmup phase.
            "pure_random": np.float32(1.0 if self.random_timesteps > 0 else 0.0),
        }

    def schedule(self, env_steps):
        return {
            "scale": np.float32(
                _anneal(self.initial_scale, self.final_scale,
                        self.scale_timesteps, env_steps)
            ),
            "pure_random": np.float32(1.0 if env_steps < self.random_timesteps else 0.0),
        }

    def actions(self, module, params, obs, key, explore, state):
        import jax
        import jax.numpy as jnp

        a = module.pi(params, obs)
        if explore:
            k1, k2 = jax.random.split(key)
            noise = jax.random.normal(k1, a.shape) * (
                self.stddev * state["scale"] * module.scale
            )
            noisy = jnp.clip(a + noise, module.act_low, module.act_high)
            rand = jax.random.uniform(
                k2, a.shape, minval=module.act_low, maxval=module.act_high
            )
            a = jnp.where(state["pure_random"] > 0, rand, noisy)
        value = module.q_values(params["q1"], obs, a)
        return a, jnp.zeros(a.shape[:-1], jnp.float32), value, a, state


class OrnsteinUhlenbeckNoise(Exploration):
    """Temporally-correlated OU noise for continuous control (reference:
    `rllib/utils/exploration/ornstein_uhlenbeck_noise.py`). The OU process
    x += theta*(-x)*dt + sigma*sqrt(dt)*N(0,1) lives in the traced state as a
    (num_envs, act_dim) array — it evolves inside jit across steps and
    persists across rollout fragments."""

    def __init__(self, ou_theta: float = 0.15, ou_sigma: float = 0.2,
                 ou_base_scale: float = 0.1, initial_scale: float = 1.0,
                 final_scale: float = 1.0, scale_timesteps: int = 10_000):
        self.ou_theta = float(ou_theta)
        self.ou_sigma = float(ou_sigma)
        self.ou_base_scale = float(ou_base_scale)
        self.initial_scale = float(initial_scale)
        self.final_scale = float(final_scale)
        self.scale_timesteps = int(scale_timesteps)

    def initial_state(self, num_envs, act_shape):
        return {
            "scale": np.float32(self.initial_scale),
            "ou": np.zeros((num_envs,) + tuple(act_shape), np.float32),
        }

    def schedule(self, env_steps):
        return {
            "scale": np.float32(
                _anneal(self.initial_scale, self.final_scale,
                        self.scale_timesteps, env_steps)
            )
        }

    def actions(self, module, params, obs, key, explore, state):
        import jax
        import jax.numpy as jnp

        a = module.pi(params, obs)
        new_state = state
        if explore:
            ou = state["ou"]
            drift = jax.random.normal(key, ou.shape)
            ou = ou + self.ou_theta * (-ou) + self.ou_sigma * drift
            noise = self.ou_base_scale * state["scale"] * ou * module.scale
            a = jnp.clip(a + noise, module.act_low, module.act_high)
            new_state = dict(state, ou=ou)
        value = module.q_values(params["q1"], obs, a)
        return a, jnp.zeros(a.shape[:-1], jnp.float32), value, a, new_state


class ParameterNoise(Exploration):
    """Adaptive parameter-space noise (reference:
    `rllib/utils/exploration/parameter_noise.py`, Plappert et al. 2018):
    the ROLLOUT acts greedily under weights perturbed once per weight sync
    with N(0, stddev) — exploration comes from a consistently-different
    policy rather than per-step action dithering. Learner weights are never
    perturbed; each sync draws a fresh perturbation."""

    def __init__(self, stddev: float = 0.05):
        self.stddev = float(stddev)

    def on_weights(self, params, key):
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(key, len(leaves))
        perturbed = [
            l + self.stddev * jax.random.normal(k, jnp.shape(l), jnp.float32)
            if hasattr(l, "dtype") and jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
            else l
            for l, k in zip(leaves, keys)
        ]
        return jax.tree_util.tree_unflatten(treedef, perturbed)

    def actions(self, module, params, obs, key, explore, state):
        # Greedy under the (already-perturbed) rollout params.
        a, logp, v, d = module.action_dist(params, obs, key, False)
        return a, logp, v, d, state


_STRATEGIES = {
    "EpsilonGreedy": EpsilonGreedy,
    "SoftQ": SoftQ,
    "StochasticSampling": StochasticSampling,
    "Random": Random,
    "GaussianNoise": GaussianNoise,
    "OrnsteinUhlenbeckNoise": OrnsteinUhlenbeckNoise,
    "ParameterNoise": ParameterNoise,
}


def build_exploration(spec: Any) -> Optional[Exploration]:
    """Resolve an exploration spec: None, an Exploration instance, or a dict
    {"type": <name-or-class>, **kwargs} (the reference's exploration_config
    format, `rllib/utils/exploration/exploration.py from_config`)."""
    if spec is None or isinstance(spec, Exploration):
        return spec
    if isinstance(spec, type) and issubclass(spec, Exploration):
        return spec()
    if isinstance(spec, dict):
        spec = dict(spec)
        typ = spec.pop("type", None)
        if typ is None:
            raise ValueError("exploration_config requires a 'type' key")
        if isinstance(typ, str):
            if typ not in _STRATEGIES:
                raise ValueError(
                    f"unknown exploration type {typ!r}; one of {sorted(_STRATEGIES)}"
                )
            typ = _STRATEGIES[typ]
        return typ(**spec)
    raise TypeError(f"unsupported exploration spec: {type(spec)}")
