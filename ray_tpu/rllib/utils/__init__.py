"""RLlib utility subpackage: exploration strategies + replay buffers.

Reference: `rllib/utils/exploration/` and `rllib/utils/replay_buffers/`.
"""

from ray_tpu.rllib.utils.exploration import (
    EpsilonGreedy,
    Exploration,
    GaussianNoise,
    OrnsteinUhlenbeckNoise,
    ParameterNoise,
    Random,
    SoftQ,
    StochasticSampling,
    build_exploration,
)
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)

__all__ = [
    "Exploration",
    "EpsilonGreedy",
    "SoftQ",
    "StochasticSampling",
    "Random",
    "GaussianNoise",
    "OrnsteinUhlenbeckNoise",
    "ParameterNoise",
    "build_exploration",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
]
