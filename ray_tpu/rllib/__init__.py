"""ray_tpu.rllib: reinforcement learning on the actor substrate, JAX-native.

Reference: `rllib/` (P20 in SURVEY.md §2) — `Algorithm(Trainable)`
(`algorithms/algorithm.py:149`, `training_step:1336`), sampling workers
(`evaluation/rollout_worker.py:166`), and the new Learner stack
(`core/learner/learner.py:100`, `learner_group.py:48`, `core/rl_module/`).

TPU-first: where the reference's `TorchLearner` wraps modules in DDP for grad
sync (`torch_learner.py:143-194`), `JaxLearner`'s update is ONE jitted SPMD
function over a device mesh — grads sync via the mesh's data axis inside XLA
(psum over ICI), not an external DDP hook. Sampling stays on CPU actors
(vectorized gymnasium envs); only the learner touches accelerator devices.
"""

from ray_tpu.rllib.callbacks import DefaultCallbacks, Episode
from ray_tpu.rllib.core.distributional import (
    DistributionalQModule,
    DuelingQMLPModule,
)
from ray_tpu.rllib.core.rl_module import (
    DeterministicContinuousModule,
    MLPModule,
    RLModule,
    SquashedGaussianModule,
)
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.env.env_runner import EnvRunner
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv, make_multi_agent
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig, Impala, ImpalaConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.pg import PG, PGConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.td3 import DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.connectors import (
    ClipActions,
    ClipObs,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    NormalizeObs,
    UnsquashActions,
)
from ray_tpu.rllib.models import MODEL_DEFAULTS, ModelCatalog, register_custom_module
from ray_tpu.rllib.utils.exploration import Exploration, build_exploration
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer

__all__ = [
    "A2C",
    "A2CConfig",
    "APPO",
    "APPOConfig",
    "Algorithm",
    "AlgorithmConfig",
    "ApexDQN",
    "ApexDQNConfig",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "ClipActions",
    "ClipObs",
    "Connector",
    "ConnectorPipeline",
    "DDPGConfig",
    "DQN",
    "DQNConfig",
    "DefaultCallbacks",
    "Episode",
    "DeterministicContinuousModule",
    "DistributionalQModule",
    "DuelingQMLPModule",
    "EnvRunner",
    "Exploration",
    "build_exploration",
    "PrioritizedReplayBuffer",
    "ReplayBuffer",
    "FlattenObs",
    "IMPALA",
    "IMPALAConfig",
    "Impala",
    "ImpalaConfig",
    "JaxLearner",
    "LearnerGroup",
    "MARWIL",
    "MARWILConfig",
    "MLPModule",
    "MODEL_DEFAULTS",
    "ModelCatalog",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "NormalizeObs",
    "PG",
    "PGConfig",
    "PPO",
    "PPOConfig",
    "RLModule",
    "SAC",
    "SACConfig",
    "SquashedGaussianModule",
    "TD3",
    "TD3Config",
    "UnsquashActions",
    "make_multi_agent",
    "register_custom_module",
]
