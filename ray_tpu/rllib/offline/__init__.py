"""ray_tpu.rllib.offline: offline-RL data input/output.

Reference: `rllib/offline/` — `InputReader` (`input_reader.py`), JSON
readers/writers (`json_reader.py`, `json_writer.py`), and the Ray-Data-backed
`DatasetReader` (`dataset_reader.py`). Batches are dicts of numpy columns
over transitions; JSON files hold one episode (or fragment) per line.
"""

from ray_tpu.rllib.offline.input_reader import InputReader
from ray_tpu.rllib.offline.json_reader import JsonReader
from ray_tpu.rllib.offline.json_writer import JsonWriter
from ray_tpu.rllib.offline.dataset_reader import DatasetReader

__all__ = ["DatasetReader", "InputReader", "JsonReader", "JsonWriter"]
