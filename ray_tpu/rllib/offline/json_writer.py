"""JsonWriter: persist experience batches as JSON-lines files.

Reference: `rllib/offline/json_writer.py` — each `write()` emits one line
holding the batch's columns. Write episode-complete batches so readers can
compute exact Monte-Carlo returns (MARWIL); the trailing row of a complete
episode has terminateds/truncateds true.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


class JsonWriter:
    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        self.max_file_size = max_file_size
        self._file_index = 0
        self._fh: Optional[Any] = None
        os.makedirs(path, exist_ok=True)

    def _file(self):
        if self._fh is None or self._fh.tell() > self.max_file_size:
            if self._fh is not None:
                self._fh.close()
                self._file_index += 1
            name = os.path.join(self.path, f"output-{self._file_index:05d}.json")
            self._fh = open(name, "a")
        return self._fh

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        fh = self._file()
        fh.write(json.dumps(row) + "\n")
        fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
