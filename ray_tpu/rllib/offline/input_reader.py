"""InputReader: the offline-data input seam.

Reference: `rllib/offline/input_reader.py` — `next()` returns one batch of
experience. Implementations: `JsonReader`, `DatasetReader`, or any callable
the user passes to `config.offline_data(input_=...)` returning a reader.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class InputReader:
    def next(self) -> Dict[str, np.ndarray]:
        """Return the next batch of experiences (numpy columns over
        transitions; at minimum `obs` and `actions`)."""
        raise NotImplementedError
