"""DatasetReader: serve offline batches from a `ray_tpu.data.Dataset`.

Reference: `rllib/offline/dataset_reader.py` — the Ray-Data-backed input
path (`get_dataset_and_shards` + per-worker iteration). Rows are transitions
with at least `obs` and `actions` columns; iteration cycles the dataset with
a fresh shuffle-free pass per epoch (shuffle upstream via `ds.random_shuffle`
if desired).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from ray_tpu.rllib.offline.input_reader import InputReader


class DatasetReader(InputReader):
    def __init__(self, dataset, batch_size: int = 256):
        self.dataset = dataset
        self.batch_size = batch_size
        self._it: Optional[Iterator] = None

    def _iter(self):
        if self._it is None:
            # drop_last keeps every served batch exactly batch_size rows so
            # the jitted learner update compiles once, not once per tail.
            self._it = iter(
                self.dataset.iter_batches(
                    batch_size=self.batch_size,
                    batch_format="numpy",
                    drop_last=True,
                )
            )
        return self._it

    def next(self) -> Dict[str, np.ndarray]:
        try:
            batch = next(self._iter())
        except StopIteration:
            self._it = None
            try:
                batch = next(self._iter())
            except StopIteration:
                raise ValueError(
                    f"dataset holds fewer than batch_size={self.batch_size} "
                    "rows; lower the batch size or add data"
                ) from None
        out = {k: np.asarray(v) for k, v in batch.items()}
        # Terminal flags: transitions from a Dataset are treated as i.i.d.
        # rows; a missing `dones` column means no episode structure (BC-style
        # losses don't need one; MARWIL's return computation requires it).
        return out
