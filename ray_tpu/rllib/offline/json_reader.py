"""JsonReader: sample batches from JSON-lines experience files.

Reference: `rllib/offline/json_reader.py` — reads the files produced by
`JsonWriter` (one episode/fragment batch per line), shuffles at the line
level, and serves fixed-size transition batches. Episode boundaries are
preserved in `dones` so return computation never leaks across lines: a
synthetic done closes each line's tail even for fragments.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Sequence, Union

import numpy as np

from ray_tpu.rllib.offline.input_reader import InputReader


def _expand(paths: Union[str, Sequence[str]]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            files.extend(sorted(glob.glob(p)) or [p])
    if not files:
        raise FileNotFoundError(f"no offline data files match {paths!r}")
    return files


class JsonReader(InputReader):
    def __init__(self, inputs: Union[str, Sequence[str]],
                 batch_size: int = 256, seed: int = 0):
        self.files = _expand(inputs)
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)
        self._episodes: List[Dict[str, np.ndarray]] = []
        for fname in self.files:
            with open(fname) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    ep = {k: np.asarray(v) for k, v in row.items()}
                    n = len(ep["actions"])
                    # Close the line's tail so per-batch return computation
                    # treats every line as a self-contained segment.
                    dones = np.zeros(n, np.float32)
                    for key in ("dones", "terminateds", "truncateds"):
                        if key in ep:
                            dones = np.maximum(
                                dones, np.asarray(ep[key], np.float32)
                            )
                    dones[-1] = 1.0
                    ep["dones"] = dones
                    self._episodes.append(ep)
        if not self._episodes:
            raise ValueError(f"offline files {self.files} contain no batches")
        self._order = self._rng.permutation(len(self._episodes))
        self._cursor = 0

    def _next_episode(self) -> Dict[str, np.ndarray]:
        if self._cursor >= len(self._order):
            self._order = self._rng.permutation(len(self._episodes))
            self._cursor = 0
        ep = self._episodes[self._order[self._cursor]]
        self._cursor += 1
        return ep

    def next(self) -> Dict[str, np.ndarray]:
        """Concatenate whole episodes until `batch_size` transitions."""
        chunks: List[Dict[str, np.ndarray]] = []
        rows = 0
        while rows < self.batch_size:
            ep = self._next_episode()
            chunks.append(ep)
            rows += len(ep["actions"])
        keys = set(chunks[0])
        for c in chunks[1:]:
            keys &= set(c)
        return {
            k: np.concatenate([np.asarray(c[k]) for c in chunks]) for k in keys
        }
