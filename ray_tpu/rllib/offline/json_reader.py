"""JsonReader: sample batches from JSON-lines experience files.

Reference: `rllib/offline/json_reader.py` — reads the files produced by
`JsonWriter` (one episode/fragment batch per line), shuffles at the line
level, and serves fixed-size transition batches. Episode boundaries are
preserved in `dones` so return computation never leaks across lines: a
synthetic done closes each line's tail even for fragments.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Sequence, Union

import numpy as np

from ray_tpu.rllib.offline.input_reader import InputReader


def _expand(paths: Union[str, Sequence[str]]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            files.extend(sorted(glob.glob(p)) or [p])
    if not files:
        raise FileNotFoundError(f"no offline data files match {paths!r}")
    return files


class JsonReader(InputReader):
    """Streams one file at a time (files are bounded by the writer's
    `max_file_size`), shuffling file order per epoch and episode order within
    each file — the whole dataset is never resident (reference: the streaming
    `json_reader.py` shuffles at file granularity the same way)."""

    def __init__(self, inputs: Union[str, Sequence[str]],
                 batch_size: int = 256, seed: int = 0):
        self.files = _expand(inputs)
        missing = [f for f in self.files if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(f"offline data files not found: {missing}")
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)
        self._file_order: List[int] = []
        self._loaded: List[Dict[str, np.ndarray]] = []
        self._cursor = 0

    @staticmethod
    def _parse_line(line: str) -> Dict[str, np.ndarray]:
        row = json.loads(line)
        ep = {k: np.asarray(v) for k, v in row.items()}
        n = len(ep["actions"])
        # Close the line's tail so per-batch return computation treats
        # every line as a self-contained segment.
        dones = np.zeros(n, np.float32)
        for key in ("dones", "terminateds", "truncateds"):
            if key in ep:
                dones = np.maximum(dones, np.asarray(ep[key], np.float32))
        dones[-1] = 1.0
        ep["dones"] = dones
        return ep

    def _load_next_file(self) -> None:
        """Parse one file's episodes into the serving window."""
        attempts = 0
        while not self._loaded:
            if not self._file_order:
                if attempts >= len(self.files):
                    raise ValueError(
                        f"offline files {self.files} contain no batches"
                    )
                self._file_order = list(
                    self._rng.permutation(len(self.files))
                )
            fname = self.files[self._file_order.pop()]
            attempts += 1
            with open(fname) as fh:
                episodes = [
                    self._parse_line(line)
                    for line in fh
                    if line.strip()
                ]
            self._rng.shuffle(episodes)
            self._loaded = episodes
            self._cursor = 0

    def _next_episode(self) -> Dict[str, np.ndarray]:
        if self._cursor >= len(self._loaded):
            self._loaded = []
            self._load_next_file()
        ep = self._loaded[self._cursor]
        self._cursor += 1
        return ep

    def next(self) -> Dict[str, np.ndarray]:
        """Concatenate whole episodes until `batch_size` transitions."""
        chunks: List[Dict[str, np.ndarray]] = []
        rows = 0
        while rows < self.batch_size:
            ep = self._next_episode()
            chunks.append(ep)
            rows += len(ep["actions"])
        keys = set(chunks[0])
        for c in chunks[1:]:
            keys &= set(c)
        return {
            k: np.concatenate([np.asarray(c[k]) for c in chunks]) for k in keys
        }
