"""RLlib callbacks: user hooks into the algorithm + sampling lifecycle.

Reference: `rllib/algorithms/callbacks.py` (`DefaultCallbacks` —
on_algorithm_init / on_train_result / on_evaluate_start / on_evaluate_end
driver-side; on_episode_end / on_sample_end inside the rollout workers),
configured via `AlgorithmConfig.callbacks(callbacks_class)`.

Driver hooks fire in the training loop; episode/sample hooks fire INSIDE
each EnvRunner actor (the class ships to runners and instantiates there —
state mutated in a runner hook lives in that runner's process, exactly like
the reference's worker-side callbacks).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict


@dataclass
class Episode:
    """What a completed episode looks like to `on_episode_end`."""

    episode_return: float
    episode_length: int


class DefaultCallbacks:
    """Subclass and override; every hook is a no-op by default."""

    # ----------------------------------------------------------- driver-side
    def on_algorithm_init(self, *, algorithm, **kwargs) -> None:
        """After AlgorithmConfig.build() fully constructed the algorithm."""

    def on_train_result(self, *, algorithm, result: Dict[str, Any],
                        **kwargs) -> None:
        """After each train() iteration, with its metrics dict (mutable —
        additions show up in the returned result, as in the reference)."""

    def on_evaluate_start(self, *, algorithm, **kwargs) -> None:
        """Before a dedicated evaluation pass."""

    def on_evaluate_end(self, *, algorithm,
                        evaluation_metrics: Dict[str, Any], **kwargs) -> None:
        """After evaluation, with {"evaluation": metrics}."""

    # ----------------------------------------------------------- runner-side
    def on_episode_end(self, *, episode: Episode, **kwargs) -> None:
        """In the EnvRunner actor, when any env finishes an episode."""

    def on_sample_end(self, *, samples: Dict[str, Any], **kwargs) -> None:
        """In the EnvRunner actor, after each rollout fragment (the batch
        dict about to ship to the driver; mutations are visible there)."""
