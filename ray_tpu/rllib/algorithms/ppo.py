"""PPO: Proximal Policy Optimization on the JaxLearner stack.

Reference: `rllib/algorithms/ppo/ppo.py:56` (PPOConfig: `lambda_=1.0,
kl_coeff=0.2, sgd_minibatch_size=128, num_sgd_iter=30, clip_param=0.3,
vf_clip_param=10.0, kl_target=0.01` at ppo.py:100-111) and the loss in
`rllib/algorithms/ppo/ppo_torch_policy.py` (clipped surrogate over
logp_ratio, KL(prev||curr) from stored behavior dist inputs, clipped value
loss, entropy bonus); adaptive KL rule from `rllib/policy/torch_mixins.py:87`
(coeff *= 1.5 above 2*target, *= 0.5 below target/2).

TPU-first redesign: the whole loss (policy forward, surrogate, KL, value
loss) is one pure function jitted inside JaxLearner with donated state; on a
mesh the minibatch shards over the data axis and gradient all-reduce happens
inside XLA over ICI. GAE postprocessing stays on the host (numpy over the
(T, N) rollout buffers) — it is O(T*N) bookkeeping, not MXU work.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_ = 0.95
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.minibatch_size = 128
        self.num_epochs = 4
        self.grad_clip = 0.5
        self.use_critic = True
        self._algo_cls = PPO

    def training(self, **kwargs) -> "PPOConfig":
        # Accept the reference's old-stack names as aliases.
        aliases = {"sgd_minibatch_size": "minibatch_size", "num_sgd_iter": "num_epochs"}
        kwargs = {aliases.get(k, k): v for k, v in kwargs.items()}
        super().training(**kwargs)
        return self


def compute_gae(
    rollout: Dict[str, np.ndarray], gamma: float, lambda_: float
) -> Dict[str, np.ndarray]:
    """GAE(lambda) over a (T, N) rollout fragment with bootstrapped tails.

    Reference semantics: `rllib/evaluation/postprocessing.py`
    (`compute_advantages`) — advantages from reversed TD(lambda) residuals,
    value targets = advantages + values.
    """
    rewards, values, dones = rollout["rewards"], rollout["values"], rollout["dones"]
    # Truncation (time limit) is not termination: the advantage chain still
    # stops at the boundary, but the TD residual bootstraps through
    # V(final_obs) instead of zero (reference: compute_advantages uses
    # vf(last_obs) at time-limit cuts). Rollouts lacking the split fall back
    # to treating every done as terminal.
    terminateds = rollout.get("terminateds")
    boot = rollout.get("bootstrap_values")
    if terminateds is None or boot is None:
        # Without BOTH the term/trunc split and the final-obs values there is
        # nothing safe to bootstrap truncations through — treat every done as
        # terminal rather than leak V(reset_obs) across episode boundaries.
        terminateds, boot = dones, None
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros(rewards.shape[1], np.float32)
    for t in reversed(range(T)):
        next_values = rollout["last_values"] if t == T - 1 else values[t + 1]
        if boot is not None:
            truncated = dones[t] * (1.0 - terminateds[t])
            next_values = np.where(truncated > 0, boot[t], next_values)
        nonterminal = 1.0 - terminateds[t]
        delta = rewards[t] + gamma * next_values * nonterminal - values[t]
        lastgaelam = delta + gamma * lambda_ * (1.0 - dones[t]) * lastgaelam
        adv[t] = lastgaelam
    return {"advantages": adv, "value_targets": adv + values}


def _flatten(rollout: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """(T, N, ...) buffers -> (T*N, ...) flat transition batch."""
    out = {}
    for k, v in rollout.items():
        if k in ("last_values", "last_obs"):
            continue
        out[k] = v.reshape((-1,) + v.shape[2:])
    return out


def make_ppo_loss(config: PPOConfig) -> Callable:
    """Pure (module, params, batch) -> (loss, aux) for JaxLearner.jit."""
    clip = config.clip_param
    vf_clip = config.vf_clip_param
    vf_coeff = config.vf_loss_coeff
    ent_coeff = config.entropy_coeff
    use_critic = config.use_critic

    def loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        curr_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        logp_ratio = jnp.exp(curr_logp - batch["logp"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(
            adv * logp_ratio,
            adv * jnp.clip(logp_ratio, 1.0 - clip, 1.0 + clip),
        )
        # True KL(prev || curr) over the categorical dist, from the behavior
        # logits the runner stored (= reference's ACTION_DIST_INPUTS path).
        prev_logp_all = jax.nn.log_softmax(batch["behavior_logits"])
        kl = jnp.sum(
            jnp.exp(prev_logp_all) * (prev_logp_all - logp_all), axis=-1
        )
        mean_kl = jnp.mean(kl)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        mean_entropy = jnp.mean(entropy)
        if use_critic:
            vf_err = jnp.square(values - batch["value_targets"])
            vf_loss = jnp.clip(vf_err, 0.0, vf_clip)
            mean_vf = jnp.mean(vf_loss)
        else:
            mean_vf = jnp.asarray(0.0)
        # kl_coeff rides in the batch (per-row broadcast scalar) so the
        # adaptive-KL update never retriggers a jit compile.
        kl_coeff = jnp.mean(batch["kl_coeff"])
        policy_loss = -jnp.mean(surrogate)
        total = (
            policy_loss
            + kl_coeff * mean_kl
            + vf_coeff * mean_vf
            - ent_coeff * mean_entropy
        )
        aux = {
            "policy_loss": policy_loss,
            "vf_loss": mean_vf,
            "mean_kl": mean_kl,
            "entropy": mean_entropy,
        }
        return total, aux

    return loss


class PPO(Algorithm):
    # PPO bootstraps truncations through runner-side values (bootstrap_values)
    # and never reads final_obs: skip shipping the obs-sized buffer.
    _record_final_obs = False
    # Policy-map training via MultiAgentEnvRunner (reference: PPO rides the
    # generic multi-agent machinery in `rollout_worker.py`).
    _supports_multi_agent = True

    def __init__(self, config: PPOConfig):
        super().__init__(config)
        if self.is_multi_agent:
            self.kl_coeff = {pid: float(config.kl_coeff) for pid in self.modules}
        else:
            self.kl_coeff = float(config.kl_coeff)

    def make_loss(self) -> Callable:
        return make_ppo_loss(self.config)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    # ----------------------------------------------------------- one iteration
    def _sgd_epochs(self, learner_group, batch: Dict[str, np.ndarray],
                    kl_coeff: float) -> Tuple[Dict[str, float], float]:
        """Multi-epoch minibatch SGD on one flat batch; returns (mean metrics,
        KL sampled over the final epoch) — shared by the single- and
        multi-agent paths."""
        cfg = self.config
        a = batch["advantages"]
        batch["advantages"] = (a - a.mean()) / max(1e-4, a.std())
        B = len(batch["advantages"])
        mb = min(cfg.minibatch_size, B)
        if cfg.num_learners > 1:
            mb = max(cfg.num_learners, mb - mb % cfg.num_learners)
        if mb > B:
            raise ValueError(
                f"train batch of {B} rows is smaller than num_learners="
                f"{cfg.num_learners}; sample more steps per iteration"
            )
        metrics_acc: List[Dict[str, float]] = []
        rng = np.random.default_rng(cfg.seed + self.iteration)
        mb_per_epoch = 0
        for epoch in range(cfg.num_epochs):
            perm = rng.permutation(B)
            mb_per_epoch = 0
            for start in range(0, B - mb + 1, mb):
                idx = perm[start : start + mb]
                minibatch = {k: v[idx] for k, v in batch.items()}
                minibatch["kl_coeff"] = np.full(mb, kl_coeff, np.float32)
                metrics_acc.append(learner_group.update(minibatch))
                mb_per_epoch += 1
        out = {
            k: float(np.mean([m[k] for m in metrics_acc])) for k in metrics_acc[0]
        }
        sampled_kl = float(
            np.mean([m["mean_kl"] for m in metrics_acc[-mb_per_epoch:]])
        )
        out["num_env_steps_trained"] = B
        return out, sampled_kl

    def _adapt_kl(self, sampled_kl: float, current: float) -> float:
        """`torch_mixins.py:87` rule: *=1.5 above 2*target, *=0.5 below /2."""
        target = self.config.kl_target
        if sampled_kl > 2.0 * target:
            return current * 1.5
        if sampled_kl < 0.5 * target:
            return current * 0.5
        return current

    def _training_step_multi_agent(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.config
        weights = {pid: lg.get_weights() for pid, lg in self.learner_groups.items()}
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        samples = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        out: Dict[str, Any] = {}
        total_steps = 0
        train_set = cfg.policies_to_train or list(self.learner_groups)
        for pid, lg in self.learner_groups.items():
            chunks = [s[pid] for s in samples if pid in s]
            if not chunks:
                continue
            batch = {
                k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]
            }
            total_steps += len(batch["advantages"])
            if pid not in train_set:
                continue
            metrics, sampled_kl = self._sgd_epochs(lg, batch, self.kl_coeff[pid])
            self.kl_coeff[pid] = self._adapt_kl(sampled_kl, self.kl_coeff[pid])
            metrics["kl_coeff"] = self.kl_coeff[pid]
            for k, v in metrics.items():
                out[f"policy_{pid}/{k}"] = v
        out["num_env_steps_sampled"] = total_steps
        return self.collect_episode_metrics(out)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        if self.is_multi_agent:
            return self._training_step_multi_agent()
        cfg = self.config
        # 1. Push current weights to all samplers.
        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        # 2. Parallel rollouts.
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        # 3. GAE on the host, then one flat train batch.
        flats: List[Dict[str, np.ndarray]] = []
        for ro in rollouts:
            ro = dict(ro)
            ro.update(compute_gae(ro, cfg.gamma, cfg.lambda_))
            flats.append(_flatten(ro))
        # Only the keys the loss consumes ride into the jitted update.
        keys = (
            "obs",
            "actions",
            "logp",
            "behavior_logits",
            "advantages",
            "value_targets",
        )
        batch = {k: np.concatenate([f[k] for f in flats]) for k in keys}
        B = len(batch["advantages"])
        # 4. Standardized advantages + multi-epoch minibatch SGD, then the
        # adaptive KL update on the final epoch's sampled KL.
        out, sampled_kl = self._sgd_epochs(self.learner_group, batch, self.kl_coeff)
        self.kl_coeff = self._adapt_kl(sampled_kl, self.kl_coeff)
        out["kl_coeff"] = self.kl_coeff
        out["num_env_steps_sampled"] = B
        return self.collect_episode_metrics(out)

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        return {"kl_coeff": self.kl_coeff}

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        kl = state.get("kl_coeff", self.config.kl_coeff)
        self.kl_coeff = dict(kl) if isinstance(kl, dict) else float(kl)
