"""IMPALA: importance-weighted actor-learner architecture with V-trace.

Reference: `rllib/algorithms/impala/impala.py` (ImpalaConfig: `vtrace=True,
vtrace_clip_rho_threshold=1.0, vtrace_clip_pg_rho_threshold=1.0,
entropy_coeff=0.01, vf_loss_coeff=0.5, grad_clip=40`) and the V-trace math in
`rllib/algorithms/impala/vtrace_torch.py` (Espeholt et al. 2018, eq. 1):
vs_t = V(x_t) + sum_k gamma^k (prod c) rho_k delta_k, computed as a reverse
recursion; policy gradient uses rho_t (r_t + gamma vs_{t+1} - V(x_t)).

TPU-first shape: the whole V-trace computation lives INSIDE the jitted loss
as a `lax.scan` over the time axis — batches keep their (N, T) structure and
shard over the env axis (data axis of the mesh), so every learner computes
V-trace on its own shard with zero cross-device traffic until the gradient
all-reduce. The reference computes v-trace in torch on flattened
sequences per rollout; here the learner consumes rollouts directly (no GAE
preprocessing pass on the host at all — the correction IS the target
computation). Truncated (time-limit) episodes bootstrap through
V(final_obs) evaluated with the CURRENT parameters inside the loss, not the
stale behavior-policy value the runner saw.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_pg_rho_threshold = 1.0
        self.vtrace_clip_c_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self._algo_cls = Impala


def make_impala_loss(config: ImpalaConfig) -> Callable:
    """Pure (module, params, batch) -> (loss, aux). Batch arrays are (N, T,
    ...) — env-major so the leading axis shards over the mesh's data axis."""
    gamma = config.gamma
    rho_bar = config.vtrace_clip_rho_threshold
    pg_rho_bar = config.vtrace_clip_pg_rho_threshold
    c_bar = config.vtrace_clip_c_threshold
    vf_coeff = config.vf_loss_coeff
    ent_coeff = config.entropy_coeff

    def loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]            # (N, T, obs)
        actions = batch["actions"]    # (N, T)
        behavior_logp = batch["logp"]
        rewards = batch["rewards"]
        terms = batch["terminateds"]  # episode truly ended
        dones = batch["dones"]        # ended OR time limit
        truncs = batch["truncateds"]
        final_obs = batch["final_obs"]
        last_obs = batch["last_obs"]  # (N, obs)

        logits, values = module.forward(params, obs)  # (N, T, A), (N, T)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(logp_all, actions[..., None], axis=-1)[..., 0]
        _, last_values = module.forward(params, last_obs)  # (N,)
        # V(final_obs) under CURRENT params for time-limit bootstraps; rows
        # without truncation hold zeros in final_obs and their value is unused.
        _, fin_values = module.forward(params, final_obs)  # (N, T)

        rho = jnp.exp(target_logp - behavior_logp)
        clipped_rho = jnp.minimum(rho, rho_bar)
        c = jnp.minimum(rho, c_bar)

        # next-state values: V(x_{t+1}) with episode-boundary handling —
        # terminal -> 0, truncation -> V(final_obs), tail -> V(last_obs).
        next_values = jnp.concatenate([values[:, 1:], last_values[:, None]], axis=1)
        next_values = jnp.where(truncs > 0, fin_values, next_values)
        next_values = next_values * (1.0 - terms)

        delta = clipped_rho * (rewards + gamma * next_values - values)

        # Reverse scan over T: acc carries (vs_{t+1} - V(x_{t+1})); episode
        # boundaries cut the recursion (dones include truncation — the
        # correction term never leaks across resets).
        def scan_fn(acc, xs):
            delta_t, c_t, done_t = xs
            acc = delta_t + gamma * c_t * (1.0 - done_t) * acc
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            scan_fn,
            jnp.zeros(values.shape[0], values.dtype),
            (delta.T, c.T, dones.T),
            reverse=True,
        )
        vs_minus_v = vs_minus_v.T  # (N, T)
        vs = jax.lax.stop_gradient(vs_minus_v + values)

        # Policy-gradient advantage: r + gamma vs_{t+1} - V(x_t), with
        # vs_{T} = V(last_obs) and boundary handling as above.
        vs_next = jnp.concatenate([vs[:, 1:], last_values[:, None]], axis=1)
        vs_next = jnp.where(truncs > 0, fin_values, vs_next)
        vs_next = vs_next * (1.0 - terms)
        pg_adv = jax.lax.stop_gradient(
            jnp.minimum(rho, pg_rho_bar) * (rewards + gamma * vs_next - values)
        )

        pi_loss = -jnp.mean(target_logp * pg_adv)
        vf_loss = 0.5 * jnp.mean(jnp.square(values - vs))
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
        aux = {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.mean(rho),
        }
        return total, aux

    return loss


class Impala(Algorithm):
    # The loss recomputes values/bootstraps under CURRENT params (V-trace):
    # runner-side value evaluations and dist buffers would be dead weight.
    _record_value_extras = False

    def make_loss(self) -> Callable:
        return make_impala_loss(self.config)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    # ----------------------------------------------------------- one iteration
    def _sample_env_major_batch(self) -> Dict[str, np.ndarray]:
        """Sync weights, gather rollouts, and assemble the (N, T, ...)
        env-major batch the V-trace losses consume — concat over runners on
        the env axis (the axis LearnerGroup shards / the mesh data axis).
        Shared by IMPALA and APPO."""
        import ray_tpu

        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])

        def env_major(key):
            return np.concatenate(
                [np.moveaxis(ro[key], 0, 1) for ro in rollouts], axis=0
            )

        batch = {
            k: env_major(k)
            for k in (
                "obs", "actions", "logp", "rewards",
                "dones", "terminateds", "truncateds", "final_obs",
            )
        }
        batch["last_obs"] = np.concatenate([ro["last_obs"] for ro in rollouts], axis=0)
        return batch

    def training_step(self) -> Dict[str, Any]:
        batch = self._sample_env_major_batch()
        out = dict(self.learner_group.update(batch))
        out["num_env_steps_sampled"] = int(batch["rewards"].size)
        return self.collect_episode_metrics(out)


IMPALA = Impala
IMPALAConfig = ImpalaConfig
