"""MARWIL: Monotonic Advantage Re-Weighted Imitation Learning (offline RL).

Reference: `rllib/algorithms/marwil/marwil.py` (MARWILConfig: `beta=1.0,
vf_coeff=1.0, moving_average_sqd_adv_norm_start=100.0,
moving_average_sqd_adv_norm_update_rate=1e-8, lr=1e-4,
train_batch_size=2000`) and the loss in `marwil_torch_policy.py:47-112`:
logp weighted by exp(beta * adv / sqrt(moving-average |adv|^2)), value loss
0.5 * mean(adv^2); beta=0 degenerates to plain behavioral cloning (BC).

TPU-first shape: the loss is one pure jitted function; the moving-average
advantage norm rides INTO the batch as a broadcast scalar (like PPO's
kl_coeff) and the fresh `adv_squared_mean` rides OUT through aux — the
stateful EMA update stays on the host, so the jitted program needs no
mutable state and shards cleanly over remote learners.

Training is purely offline: batches come from `config.offline_data(input_=)`
(JSON-lines episodes or a `ray_tpu.data.Dataset`); Monte-Carlo returns are
computed on the host per batch, resetting at episode boundaries. `evaluate()`
rolls the greedy policy in the config's env.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-4
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.bc_logstd_coeff = 0.0
        self.moving_average_sqd_adv_norm_start = 100.0
        self.moving_average_sqd_adv_norm_update_rate = 1e-8
        self.train_batch_size = 2000
        self.updates_per_iteration = 1
        self.grad_clip: Optional[float] = None
        self.num_env_runners = 0
        self._algo_cls = MARWIL


def compute_returns(
    rewards: np.ndarray, dones: np.ndarray, gamma: float
) -> np.ndarray:
    """Discounted Monte-Carlo return per transition over a flat batch of
    concatenated episode segments; `dones` cuts the accumulation.

    Reference: MARWIL postprocesses with `compute_advantages(..., lambda=1,
    use_gae=False)` — advantages column = discounted return. The final
    segment of a batch always ends done (readers guarantee it)."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in reversed(range(len(rewards))):
        # A done row restarts the accumulation with its own reward.
        acc = rewards[t] + gamma * acc * (1.0 - dones[t])
        out[t] = acc
    return out


def make_marwil_loss(config: "MARWILConfig") -> Callable:
    """Pure (module, params, batch) -> (loss, aux) for JaxLearner.jit."""
    beta = float(config.beta)
    vf_coeff = float(config.vf_coeff)

    def loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        if beta != 0.0:
            adv = batch["returns"] - values
            adv_sq_mean = jnp.mean(jnp.square(adv))
            # EMA norm enters as a broadcast scalar (host-updated between
            # steps from the adv_squared_mean aux below).
            ma_norm = jnp.mean(batch["ma_sqd_adv_norm"])
            exp_advs = jax.lax.stop_gradient(
                jnp.exp(beta * adv / (1e-8 + jnp.sqrt(ma_norm)))
            )
            v_loss = 0.5 * adv_sq_mean
        else:
            adv_sq_mean = jnp.asarray(0.0)
            exp_advs = 1.0
            v_loss = jnp.asarray(0.0)
        p_loss = -jnp.mean(exp_advs * logp)
        total = p_loss + vf_coeff * v_loss
        aux = {
            "policy_loss": p_loss,
            "vf_loss": v_loss,
            "adv_squared_mean": adv_sq_mean,
            "mean_logp": jnp.mean(logp),
        }
        return total, aux

    return loss


class MARWIL(Algorithm):
    _needs_env_runners = False

    def __init__(self, config: MARWILConfig):
        super().__init__(config)
        self.reader = config.build_input_reader(
            batch_size=config.train_batch_size, seed=config.seed
        )
        self.ma_sqd_adv_norm = float(config.moving_average_sqd_adv_norm_start)
        self._eval_runner = None

    def make_loss(self) -> Callable:
        return make_marwil_loss(self.config)

    def make_optimizer(self):
        import optax

        if self.config.grad_clip is not None:
            return optax.chain(
                optax.clip_by_global_norm(self.config.grad_clip),
                optax.adam(self.config.lr),
            )
        return None

    # ----------------------------------------------------------- one iteration
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        for _ in range(max(1, cfg.updates_per_iteration)):
            batch = dict(self.reader.next())
            batch["obs"] = np.asarray(batch["obs"], np.float32)
            batch["actions"] = np.asarray(batch["actions"], np.int64)
            n = len(batch["actions"])
            train = {"obs": batch["obs"], "actions": batch["actions"]}
            if cfg.beta != 0.0:
                if "rewards" not in batch or "dones" not in batch:
                    raise ValueError(
                        "MARWIL (beta != 0) needs rewards + episode boundaries "
                        "(dones) in the offline data to compute returns"
                    )
                train["returns"] = compute_returns(
                    np.asarray(batch["rewards"], np.float32),
                    np.asarray(batch["dones"], np.float32),
                    cfg.gamma,
                )
                train["ma_sqd_adv_norm"] = np.full(
                    n, self.ma_sqd_adv_norm, np.float32
                )
            else:
                # BC's loss reads only obs/actions, but the learner signature
                # is fixed per-compile: ship the unused columns as zeros.
                train["returns"] = np.zeros(n, np.float32)
                train["ma_sqd_adv_norm"] = np.ones(n, np.float32)
            if n > cfg.train_batch_size:
                # Readers serve whole episodes, so row counts drift batch to
                # batch; the jitted update compiles per shape. Slice AFTER
                # return computation (truncating first would corrupt the
                # Monte-Carlo returns of the retained rows).
                train = {k: v[: cfg.train_batch_size] for k, v in train.items()}
            metrics = self.learner_group.update(train)
            if cfg.beta != 0.0:
                # Host-side EMA update (torch policy keeps this as a buffer;
                # here the jitted loss stays pure).
                rate = cfg.moving_average_sqd_adv_norm_update_rate
                self.ma_sqd_adv_norm += rate * (
                    metrics["adv_squared_mean"] - self.ma_sqd_adv_norm
                )
        out = dict(metrics)
        out["ma_sqd_adv_norm"] = self.ma_sqd_adv_norm
        out["num_env_steps_trained"] = (
            max(1, cfg.updates_per_iteration) * cfg.train_batch_size
        )
        return out

    # -------------------------------------------------------------- evaluation
    def evaluate(self, num_episodes: int = 10) -> Dict[str, float]:
        """Greedy rollouts in the config env (reference: `Algorithm.evaluate`
        with explore=False)."""
        from ray_tpu.rllib.env.env_runner import EnvRunner

        if self._eval_runner is None:
            self._eval_runner = EnvRunner(
                self.config.env_creator(),
                self.module,
                num_envs=2,
                rollout_length=256,
                seed=self.config.seed + 424242,
                record_value_extras=False,
                record_final_obs=False,
            )
        self._eval_runner.set_weights(self.learner_group.get_weights())
        self._eval_runner.episode_stats(clear=True)
        stats = {"episodes": 0}
        for _ in range(20):
            self._eval_runner.sample(explore=False)
            stats = self._eval_runner.episode_stats(clear=False)
            if stats["episodes"] >= num_episodes:
                break
        return stats

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        return {"ma_sqd_adv_norm": self.ma_sqd_adv_norm}

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        self.ma_sqd_adv_norm = float(
            state.get(
                "ma_sqd_adv_norm", self.config.moving_average_sqd_adv_norm_start
            )
        )
