"""Ape-X DQN: distributed prioritized experience replay.

Reference: `rllib/algorithms/apex_dqn/apex_dqn.py` (Horgan et al. 2018) —
many rollout workers with per-worker exploration feed sharded replay-buffer
ACTORS; the learner samples from the shards asynchronously and ships new
priorities back; sampling and learning are decoupled (workers are never
blocked on the learner).

TPU-first shape: rollout submission is pipelined fire-and-forget futures
(`ray_tpu.wait` harvests whichever fragments are done, pushes them to a
round-robin replay shard, and immediately resubmits that runner — the
scheduler's lease pipelining keeps runners hot); the learner stays a jitted
SPMD step on the driver's devices. Per-worker epsilons follow the reference's
`PerWorkerEpsilonGreedy` power schedule so exploration diversity comes from
the fleet, not a decayed scalar. Priorities refresh from the per-sample
|TD| the learner update itself returns (vector aux threads through the
metrics path, concatenated across shards in sample order) — no extra
weight fetch or TD forward per gradient step.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2
        self.num_replay_shards = 2
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.final_prioritized_replay_beta = 1.0
        self.beta_annealing_timesteps = 200_000
        # Per-worker exploration (reference `PerWorkerEpsilonGreedy`):
        # worker i of n holds epsilon = base ** (1 + i/(n-1) * exponent).
        self.per_worker_epsilon_base = 0.4
        self.per_worker_epsilon_exponent = 7.0
        # Max rollout fragments pushed per training_step before learning
        # (bounds driver-side harvest work; extras stay queued).
        self.max_fragments_per_step = 8
        self._algo_cls = ApexDQN


class ReplayShard:
    """Remote actor owning one PrioritizedReplayBuffer shard."""

    def __init__(self, capacity: int, alpha: float, seed: int):
        self.buf = PrioritizedReplayBuffer(capacity, alpha)
        self._rng = np.random.default_rng(seed)

    def add(self, batch: Dict[str, np.ndarray]) -> int:
        self.buf.add(batch)
        return self.buf.size

    def sample(self, batch_size: int, beta: float):
        if self.buf.size < batch_size:
            return None
        return self.buf.sample(batch_size, self._rng, beta=beta)

    def update_priorities(self, idx, priorities) -> None:
        self.buf.update_priorities(idx, priorities)

    def size(self) -> int:
        return self.buf.size

    def stats(self) -> Dict[str, float]:
        return self.buf.stats()


class ApexDQN(DQN):
    """DQN with sharded prioritized replay actors + pipelined rollouts."""

    _supports_multi_agent = False

    def __init__(self, config: ApexDQNConfig):
        import ray_tpu

        if config.exploration_config is not None:
            # Ape-X's exploration IS the per-worker epsilon power schedule;
            # a strategy would silently swallow the per-worker floats
            # (set_exploration's dict-state path has no 'epsilon' key to
            # merge into for e.g. SoftQ).
            raise ValueError(
                "ApexDQN owns per-worker epsilon-greedy exploration; "
                "exploration_config is not supported (tune "
                "per_worker_epsilon_base/exponent instead)"
            )
        if config.replay_buffer_config is not None:
            # Sharded prioritized replay actors ARE the algorithm; a uniform
            # replay_buffer_config would be silently overridden otherwise.
            raise ValueError(
                "ApexDQN always uses sharded prioritized replay; configure "
                "prioritized_replay_alpha/beta + num_replay_shards instead "
                "of replay_buffer_config"
            )
        Algorithm.__init__(self, config)
        shard_cls = ray_tpu.remote(ReplayShard)
        self.replay_shards: List[Any] = [
            shard_cls.options(num_cpus=1).remote(
                max(1, config.buffer_capacity // config.num_replay_shards),
                config.prioritized_replay_alpha,
                config.seed + 77 * i,
            )
            for i in range(config.num_replay_shards)
        ]
        self._shard_rr = 0
        self.num_updates = 0
        self.env_steps = 0
        self._rng = np.random.default_rng(config.seed)
        self._sync_target()
        # One in-flight sample() per runner, resubmitted on harvest — the
        # decoupling that makes Ape-X Ape-X.
        self._pending: Dict[Any, Any] = {}
        self._push_worker_epsilons()

    # ----------------------------------------------------------- exploration
    def worker_epsilons(self) -> List[float]:
        cfg = self.config
        n = max(1, len(self.env_runners))
        if n == 1:
            return [cfg.per_worker_epsilon_base]
        return [
            cfg.per_worker_epsilon_base
            ** (1.0 + (i / (n - 1)) * cfg.per_worker_epsilon_exponent)
            for i in range(n)
        ]

    def _push_worker_epsilons(self) -> None:
        import ray_tpu

        ray_tpu.get(
            [
                r.set_exploration.remote(eps)
                for r, eps in zip(self.env_runners, self.worker_epsilons())
            ]
        )

    def beta(self) -> float:
        from ray_tpu.rllib.utils.exploration import _anneal

        cfg = self.config
        return _anneal(
            cfg.prioritized_replay_beta,
            cfg.final_prioritized_replay_beta,
            cfg.beta_annealing_timesteps,
            self.env_steps,
        )

    # ---------------------------------------------------------- rollout plane
    def _harvest_rollouts(self) -> int:
        """Collect finished fragments, push each to a shard, resubmit the
        runner. Never blocks on stragglers beyond the first fragment."""
        import ray_tpu

        for r in self.env_runners:
            if not any(owner is r for owner in self._pending.values()):
                self._pending[r.sample.remote()] = r
        pushed = 0
        adds = []
        first = True
        while self._pending and pushed < self.config.max_fragments_per_step:
            ready, _ = ray_tpu.wait(
                list(self._pending), num_returns=1, timeout=None if first else 0.0
            )
            if not ready:
                break
            first = False
            for ref in ready:
                runner = self._pending.pop(ref)
                ro = ray_tpu.get(ref)
                trans = self._transitions(
                    ro, self.config.n_step, self.config.gamma
                )
                shard = self.replay_shards[self._shard_rr % len(self.replay_shards)]
                self._shard_rr += 1
                adds.append(shard.add.remote(trans))
                self.env_steps += int(ro["rewards"].size)
                pushed += 1
                self._pending[runner.sample.remote()] = runner
        ray_tpu.get(adds)  # adds are tiny; barrier keeps size metrics honest
        return pushed

    # ------------------------------------------------------------ train plane
    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.config
        weights = self.learner_group.get_weights()
        # Fire-and-forget: each runner applies the new weights after its
        # in-flight fragment (standard Ape-X staleness). A barrier here would
        # queue behind every runner's pending sample() and re-couple the
        # learner to the slowest runner.
        for r in self.env_runners:
            r.set_weights.remote(weights)
        pushed = self._harvest_rollouts()
        beta = self.beta()
        sizes = ray_tpu.get([s.size.remote() for s in self.replay_shards])
        out: Dict[str, Any] = {
            "num_env_steps_sampled": self.env_steps,
            "replay_shard_sizes": sizes,
            "fragments_pushed": pushed,
            "beta": beta,
            "worker_epsilons": self.worker_epsilons(),
        }
        if sum(sizes) < cfg.learning_starts:
            return self.collect_episode_metrics(out)

        metrics_acc: List[Dict[str, float]] = []
        # Pipeline: request the NEXT shard's batch while updating on the
        # current one.
        def request(i: int):
            shard = self.replay_shards[i % len(self.replay_shards)]
            return shard, shard.sample.remote(cfg.train_batch_size, beta)

        nxt = request(0)
        for u in range(cfg.updates_per_iteration):
            shard, ref = nxt
            batch = ray_tpu.get(ref)
            if u + 1 < cfg.updates_per_iteration:
                nxt = request(u + 1)
            if batch is None:
                continue
            idx = batch.pop("batch_indexes")
            m = self.learner_group.update(batch)
            td = np.asarray(m.pop("td_abs"))
            metrics_acc.append(m)
            self.num_updates += 1
            # Per-sample |TD| from the update itself -> new shard priorities
            # (no weight re-fetch / second TD forward per gradient step —
            # that doubled host<->device transfers).
            shard.update_priorities.remote(idx[: len(td)], td)
            if self.num_updates % cfg.target_network_update_freq == 0:
                self._sync_target()
        if metrics_acc:
            out.update(
                {k: float(np.mean([m[k] for m in metrics_acc])) for k in metrics_acc[0]}
            )
            out["num_updates"] = self.num_updates
        return self.collect_episode_metrics(out)

    def stop(self) -> None:
        import ray_tpu

        super().stop()
        for s in self.replay_shards:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
