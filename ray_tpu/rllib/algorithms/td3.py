"""TD3: twin-delayed deep deterministic policy gradient (continuous control).

Reference: `rllib/algorithms/td3/td3.py` (TD3Config over DDPG:
`twin_q=True, policy_delay=2, smooth_target_policy=True,
target_noise=0.2, target_noise_clip=0.5, critic_lr=1e-3, actor_lr=1e-3,
tau=5e-3`) and the loss in `ddpg_torch_policy.py` (critic: mse on
Q(s,a) - y with y = r + gamma * min twin target Q(s', pi_t(s') + clipped
noise); actor: -Q1(s, pi(s)); delayed policy updates). DDPG is the
degenerate config (policy_delay=1, no smoothing, single Q).

TPU-first shape: both objectives are ONE pure jitted loss with
stop-gradients carving the actor/critic split; the delayed policy update
rides as a 0/1 `actor_weight` batch column (shape-stable — no recompile on
the delay schedule); target policy smoothing noise is pre-drawn on the host
and clipped inside the jitted loss; all three target nets live in the
learner's replicated extra state with the polyak blend in `extra_update_fn`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import DQN, ReplayBuffer


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.gamma = 0.99
        self.tau = 5e-3
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        self.train_batch_size = 128
        self.updates_per_iteration = 64
        self.policy_delay = 2
        self.target_noise = 0.2
        self.target_noise_clip = 0.5
        self.explore_noise = 0.1
        self.grad_clip = 10.0
        self.model = {"hiddens": (256, 256)}
        self._algo_cls = TD3

    def training(self, **kwargs) -> "TD3Config":
        aliases = {"smooth_target_policy": None}  # accepted, always on
        kwargs = {k: v for k, v in kwargs.items() if k not in aliases}
        super().training(**kwargs)
        return self


def make_td3_loss(config: TD3Config) -> Callable:
    gamma = config.gamma
    noise_clip = float(config.target_noise_clip)

    def loss(module, params, batch, extra):
        import jax
        import jax.numpy as jnp

        sg = jax.lax.stop_gradient

        # --- critic: smoothed deterministic target action ------------------
        smooth = jnp.clip(batch["target_noise"], -noise_clip, noise_clip)
        # `extra` is params-shaped ({"pi","q1","q2"}): module.pi reads its
        # "pi" tower directly.
        a_next = jnp.clip(
            module.pi(extra, batch["next_obs"]) + smooth * module.scale,
            module.act_low,
            module.act_high,
        )
        q1t = module.q_values(extra["q1"], batch["next_obs"], a_next)
        q2t = module.q_values(extra["q2"], batch["next_obs"], a_next)
        y = sg(
            batch["rewards"]
            + gamma * (1.0 - batch["terminateds"]) * jnp.minimum(q1t, q2t)
        )
        q1 = module.q_values(params["q1"], batch["obs"], batch["actions"])
        q2 = module.q_values(params["q2"], batch["obs"], batch["actions"])
        critic_loss = jnp.mean(jnp.square(q1 - y)) + jnp.mean(jnp.square(q2 - y))

        # --- actor: through frozen critics, gated by the delay column ------
        a_pi = module.pi(params, batch["obs"])
        actor_obj = -jnp.mean(module.q_values(sg(params["q1"]), batch["obs"], a_pi))
        # actor_weight is all-ones on policy-update rounds, all-zeros
        # otherwise (a per-row column so remote-learner batch slicing works).
        actor_gate = jnp.mean(batch["actor_weight"])
        total = critic_loss + actor_gate * actor_obj
        aux = {
            "critic_loss": critic_loss,
            "actor_loss": actor_obj,
            "q_mean": jnp.mean(q1),
            "td_error_mean": jnp.mean(jnp.abs(q1 - y)),
        }
        return total, aux

    return loss


class TD3(Algorithm):
    def __init__(self, config: TD3Config):
        super().__init__(config)
        self.buffer = ReplayBuffer(config.buffer_capacity)
        self.num_updates = 0
        self.env_steps = 0
        self._rng = np.random.default_rng(config.seed)
        # Targets start as copies of the online nets (all three towers).
        w = self.learner_group.get_weights()
        self.learner_group.set_extra(
            {"pi": w["pi"], "q1": w["q1"], "q2": w["q2"]}
        )

    def make_module_continuous(self, obs_dim: int, act_space):
        from ray_tpu.rllib.models.catalog import ModelCatalog

        module = ModelCatalog.get_module(
            "deterministic_continuous", obs_dim, act_space, self.config.model
        )
        module.explore_noise = float(self.config.explore_noise)
        return module

    def make_module(self, obs_dim: int, num_actions: int):
        raise NotImplementedError(
            "TD3 targets continuous (Box) action spaces"
        )

    def make_loss(self) -> Callable:
        return make_td3_loss(self.config)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    def make_extra_update(self) -> Callable:
        tau = self.config.tau

        def polyak(new_params, extra):
            import jax

            online = {
                "pi": new_params["pi"],
                "q1": new_params["q1"],
                "q2": new_params["q2"],
            }
            return jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o, extra, online
            )

        return polyak

    # ----------------------------------------------------------- one iteration
    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.config
        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        for ro in rollouts:
            self.buffer.add(DQN._transitions(ro))
            self.env_steps += int(ro["rewards"].size)

        out: Dict[str, Any] = {
            "buffer_size": self.buffer.size,
            "num_env_steps_sampled": self.env_steps,
        }
        act_dim = self.module.act_dim
        if self.buffer.size >= cfg.learning_starts:
            metrics_acc: List[Dict[str, float]] = []
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                B = len(batch["rewards"])
                batch["target_noise"] = (
                    self._rng.standard_normal((B, act_dim)).astype(np.float32)
                    * cfg.target_noise
                )
                gate = 1.0 if self.num_updates % cfg.policy_delay == 0 else 0.0
                batch["actor_weight"] = np.full(B, gate, np.float32)
                metrics_acc.append(self.learner_group.update(batch))
                self.num_updates += 1
            out.update(
                {k: float(np.mean([m[k] for m in metrics_acc])) for k in metrics_acc[0]}
            )
        return self.collect_episode_metrics(out)

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        import jax

        return {
            "targets": jax.tree.map(
                lambda x: np.asarray(x), self.learner_group.get_extra()
            ),
            "num_updates": self.num_updates,
            "env_steps": self.env_steps,
        }

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        if state.get("targets") is not None:
            self.learner_group.set_extra(state["targets"])
        self.num_updates = int(state.get("num_updates", 0))
        self.env_steps = int(state.get("env_steps", 0))


class DDPGConfig(TD3Config):
    """DDPG as the degenerate TD3 (reference: `rllib/algorithms/ddpg/` —
    TD3 is DDPG + twin critics + delay + smoothing; running TD3's machinery
    with policy_delay=1 and no smoothing noise recovers DDPG's update)."""

    def __init__(self):
        super().__init__()
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.0
