"""PG: vanilla policy gradient (REINFORCE).

Reference: `rllib/algorithms/pg/pg.py` + `pg_torch_policy.py` — loss is
-mean(logp * cumulative_discounted_return); no critic, no clipping. The
return computation reuses MARWIL's episode-boundary-aware Monte-Carlo
accumulation; returns are batch-standardized as a variance-reducing
baseline (the reference leaves standardization to `post_process_advantages`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.marwil import compute_returns


class PGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 4e-3
        self.entropy_coeff = 0.0
        # REINFORCE consumes COMPLETE episodes (the reference uses
        # batch_mode="complete_episodes"); with fixed-fragment runners the
        # fragment must cover the env's episode length or long (good!)
        # episodes get discarded and training plateaus near the fragment
        # size. Default high; match it to your env's time limit.
        self.rollout_fragment_length = 512
        self._algo_cls = PG


def make_pg_loss(config: "PGConfig") -> Callable:
    ent_coeff = config.entropy_coeff

    def loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, _values = module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        pg_loss = -jnp.mean(logp * batch["returns"])
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss - ent_coeff * entropy
        return total, {"policy_loss": pg_loss, "entropy": entropy}

    return loss


class PG(Algorithm):
    # No critic: the runner skips value/dist buffers and bootstrap forwards.
    _record_value_extras = False
    _record_final_obs = False

    def make_loss(self) -> Callable:
        return make_pg_loss(self.config)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.config
        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        obs, actions, returns = [], [], []
        for ro in rollouts:
            T, N = ro["rewards"].shape
            # Per-env columns are contiguous trajectories; compute returns
            # column-wise with episode cuts, dropping the unfinished tail
            # (REINFORCE needs complete episodes — a truncated tail's return
            # is not observable).
            for env in range(N):
                dones = ro["dones"][:, env]
                last_done = int(np.max(np.nonzero(dones)[0])) if dones.any() else -1
                if last_done < 0:
                    continue
                sl = slice(0, last_done + 1)
                obs.append(ro["obs"][sl, env])
                actions.append(ro["actions"][sl, env])
                returns.append(
                    compute_returns(ro["rewards"][sl, env], dones[sl], cfg.gamma)
                )
        if not obs:
            return self.collect_episode_metrics({"num_env_steps_sampled": 0})
        batch = {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(actions),
            "returns": np.concatenate(returns).astype(np.float32),
        }
        r = batch["returns"]
        batch["returns"] = (r - r.mean()) / max(1e-4, r.std())
        n = len(r)
        if n > 256:
            # Complete-episode batches vary in size every iteration and the
            # jitted update compiles per shape: trim to a 256 multiple so
            # sizes land in a small reused set (rows are independent in the
            # REINFORCE loss; the trim just discards a few transitions).
            keep = (n // 256) * 256
            batch = {k: v[:keep] for k, v in batch.items()}
        out = dict(self.learner_group.update(batch))
        out["num_env_steps_sampled"] = n
        return self.collect_episode_metrics(out)
