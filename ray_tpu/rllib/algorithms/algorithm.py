"""Algorithm + AlgorithmConfig: the RLlib training driver.

Reference: `rllib/algorithms/algorithm.py:149` (`Algorithm(Trainable)`,
`training_step:1336`) and `algorithm_config.py` (fluent config:
`.environment().training().env_runners().resources()`). `train()` runs one
iteration: sync weights -> parallel sampling on EnvRunner actors -> learner
update(s) -> aggregated metrics.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np


class AlgorithmConfig:
    def __init__(self):
        self.env: Union[str, Callable, None] = None
        self.env_config: Dict[str, Any] = {}
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 512
        self.seed = 0
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 64
        self.num_learners = 0  # 0 = local learner in the driver process
        self.model: Dict[str, Any] = {"hiddens": (64, 64)}
        self.framework_str = "jax"
        # Multi-agent (reference `algorithm_config.py` `.multi_agent()`):
        # policies maps policy_id -> None (spaces inferred from the env's
        # per-agent dicts via policy_mapping_fn). Empty = single-agent.
        self.policies: Dict[str, Any] = {}
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        self.policies_to_train: Optional[List[str]] = None
        # Offline data (reference `.offline_data(input_=...)`): a path/glob/
        # list of JSON-lines files, a ray_tpu.data.Dataset, an InputReader,
        # or a zero-arg callable returning an InputReader.
        self.input_: Any = None
        # Connector specs (reference `rllib/connectors/`): a Connector
        # instance, a factory callable, or a list of either — built fresh
        # inside each runner actor.
        self.env_to_module_connector: Any = None
        self.module_to_env_connector: Any = None
        # Evaluation (reference `.evaluation(...)`,
        # `algorithm.py:847 evaluate()`): a dedicated eval-runner fleet
        # sampling with its own explore setting every `evaluation_interval`
        # training iterations for `evaluation_duration` episodes/timesteps.
        self.evaluation_interval: Optional[int] = None
        self.evaluation_duration: int = 10
        self.evaluation_duration_unit: str = "episodes"
        self.evaluation_num_env_runners: int = 1
        self.evaluation_explore: bool = False
        # Exploration (reference `.exploration(exploration_config=...)`,
        # `rllib/utils/exploration/`): None -> each algorithm's built-in
        # default (DQN epsilon-greedy, stochastic policies sample); a dict
        # {"type": "SoftQ", ...} plugs a strategy from
        # `ray_tpu.rllib.utils.exploration` into every env runner.
        self.explore: bool = True
        self.exploration_config: Any = None
        # Lifecycle hooks (reference `AlgorithmConfig.callbacks`): a
        # DefaultCallbacks subclass, instantiated on the driver AND inside
        # each env-runner actor (episode/sample hooks run there).
        from ray_tpu.rllib.callbacks import DefaultCallbacks

        self.callbacks_class = DefaultCallbacks

    # ------------------------------------------------------------ fluent API
    def environment(self, env=None, *, env_config: Optional[dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option '{k}'")
            setattr(self, k, v)
        return self

    def env_runners(
        self,
        num_env_runners: Optional[int] = None,
        num_envs_per_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        env_to_module_connector: Any = None,
        module_to_env_connector: Any = None,
    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_runner is not None:
            self.num_envs_per_runner = num_envs_per_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def evaluation(
        self,
        evaluation_interval: Optional[int] = None,
        evaluation_duration: Optional[int] = None,
        evaluation_duration_unit: Optional[str] = None,
        evaluation_num_env_runners: Optional[int] = None,
        evaluation_explore: Optional[bool] = None,
    ) -> "AlgorithmConfig":
        """Configure the dedicated evaluation pass (reference:
        `AlgorithmConfig.evaluation`)."""
        if evaluation_interval is not None:
            self.evaluation_interval = int(evaluation_interval)
        if evaluation_duration is not None:
            self.evaluation_duration = int(evaluation_duration)
        if evaluation_duration_unit is not None:
            if evaluation_duration_unit not in ("episodes", "timesteps"):
                raise ValueError(
                    "evaluation_duration_unit must be 'episodes' or 'timesteps'"
                )
            self.evaluation_duration_unit = evaluation_duration_unit
        if evaluation_num_env_runners is not None:
            self.evaluation_num_env_runners = int(evaluation_num_env_runners)
        if evaluation_explore is not None:
            self.evaluation_explore = bool(evaluation_explore)
        return self

    def exploration(
        self,
        explore: Optional[bool] = None,
        exploration_config: Any = None,
    ) -> "AlgorithmConfig":
        """Configure exploration (reference: `AlgorithmConfig.exploration`)."""
        if explore is not None:
            self.explore = bool(explore)
        if exploration_config is not None:
            from ray_tpu.rllib.utils.exploration import build_exploration

            build_exploration(exploration_config)  # validate eagerly
            self.exploration_config = exploration_config
        return self

    def learners(self, num_learners: Optional[int] = None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def callbacks(self, callbacks_class) -> "AlgorithmConfig":
        # Reference: `AlgorithmConfig.callbacks` — set the DefaultCallbacks
        # subclass driving lifecycle hooks.
        from ray_tpu.rllib.callbacks import DefaultCallbacks

        if not (isinstance(callbacks_class, type)
                and issubclass(callbacks_class, DefaultCallbacks)):
            raise ValueError(
                "callbacks_class must be a DefaultCallbacks subclass"
            )
        self.callbacks_class = callbacks_class
        return self

    def multi_agent(
        self,
        *,
        policies=None,
        policy_mapping_fn: Optional[Callable[[str], str]] = None,
        policies_to_train: Optional[List[str]] = None,
    ) -> "AlgorithmConfig":
        """Configure the policy map (reference: `AlgorithmConfig.multi_agent`).

        `policies` is a dict policy_id -> None or an iterable of policy ids;
        module specs are inferred from the MultiAgentEnv's per-agent spaces.
        `policy_mapping_fn(agent_id) -> policy_id` routes agents; default maps
        every agent to the sole policy (valid only with one policy).
        """
        if policies is not None:
            if isinstance(policies, dict):
                self.policies = dict(policies)
            else:
                self.policies = {pid: None for pid in policies}
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        if policies_to_train is not None:
            self.policies_to_train = list(policies_to_train)
        return self

    @property
    def is_multi_agent(self) -> bool:
        return bool(self.policies)

    def offline_data(self, *, input_=None) -> "AlgorithmConfig":
        """Configure the offline input source (reference:
        `AlgorithmConfig.offline_data`). See `input_` in `__init__`."""
        if input_ is not None:
            self.input_ = input_
        return self

    def build_input_reader(self, batch_size: int, seed: int = 0):
        """Resolve `input_` into an InputReader (the offline plugin seam)."""
        from ray_tpu.rllib.offline import DatasetReader, InputReader, JsonReader

        src = self.input_
        if src is None:
            raise ValueError("offline training requires config.offline_data(input_=...)")
        if isinstance(src, InputReader):
            return src
        if isinstance(src, (str, list, tuple)):
            return JsonReader(src, batch_size=batch_size, seed=seed)
        from ray_tpu.data.dataset import Dataset

        if isinstance(src, Dataset):
            return DatasetReader(src, batch_size=batch_size)
        if callable(src):
            return src()
        raise TypeError(f"unsupported offline input source: {type(src)}")

    def framework(self, framework: str) -> "AlgorithmConfig":
        if framework != "jax":
            raise ValueError("this build is jax-native; framework must be 'jax'")
        self.framework_str = framework
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        from ray_tpu._private import usage

        usage.record_library_usage("rllib")
        algo_cls = getattr(self, "_algo_cls", None) or Algorithm
        algo = algo_cls(self.copy())
        # After the SUBCLASS finished constructing (buffers, targets, ...).
        algo.callbacks.on_algorithm_init(algorithm=algo)
        return algo

    def env_creator(self) -> Callable[[], Any]:
        env, cfg = self.env, self.env_config
        if callable(env):
            return lambda: env(cfg) if cfg else env()
        if isinstance(env, str):
            import gymnasium as gym

            return lambda: gym.make(env, **cfg)
        raise ValueError("config.environment(env=...) is required")


class Algorithm:
    """Base driver; subclasses implement make_loss() + training_step()."""

    # Whether runners record the obs-sized final_obs buffer at truncation
    # boundaries (replay/V-trace algorithms bootstrap through it; PPO uses
    # runner-side bootstrap VALUES instead and opts out of the payload).
    _record_final_obs = True
    # Whether runners record value/dist buffers (values, behavior_logits,
    # bootstrap_values, last_values). IMPALA recomputes values under current
    # params inside its loss and opts out; logp is always recorded for
    # policy-gradient modules.
    _record_value_extras = True

    def __init__(self, config: AlgorithmConfig):
        import gymnasium as gym

        from ray_tpu.rllib.core.learner_group import LearnerGroup
        from ray_tpu.rllib.env.env_runner import EnvRunner
        import ray_tpu

        from ray_tpu.rllib.utils.exploration import build_exploration

        self.config = config
        self.iteration = 0
        # Cumulative sampled env steps, maintained on EVERY algorithm: replay
        # algorithms (DQN family) advance it inside training_step; for the
        # rest, train() folds in the per-iteration num_env_steps_sampled
        # metric. Exploration schedules anneal against this — previously only
        # replay algorithms defined it, so EpsilonGreedy froze at its initial
        # value forever on PPO/A2C/PG/IMPALA/APPO.
        self.env_steps = 0
        self.callbacks = config.callbacks_class()
        # Driver-side strategy instance: owns the annealing schedule whose
        # values are pushed to runners each iteration (`exploration_push`).
        self.exploration = build_exploration(config.exploration_config)
        creator = config.env_creator()
        if config.is_multi_agent:
            self._init_multi_agent(creator)
            return
        probe = creator()
        obs_space, act_space = probe.observation_space, probe.action_space
        probe.close()
        obs_dim = int(np.prod(obs_space.shape))
        if isinstance(act_space, gym.spaces.Discrete):
            self.module = self.make_module(obs_dim, int(act_space.n))
        elif isinstance(act_space, gym.spaces.Box):
            self.module = self.make_module_continuous(obs_dim, act_space)
        else:
            raise NotImplementedError(f"unsupported action space {act_space}")
        self.learner_group = LearnerGroup(
            self.module,
            self.make_loss(),
            num_learners=config.num_learners,
            learning_rate=config.lr,
            optimizer=self.make_optimizer(),
            seed=config.seed,
            extra_update_fn=self.make_extra_update(),
        )
        if not self._needs_env_runners:
            # Offline algorithms (MARWIL/BC) train from an InputReader; the
            # env exists only for spaces + evaluation.
            self.env_runners = []
            return
        self.env_runners: List[Any] = self._make_env_runners(
            creator, config.num_env_runners, seed_base=config.seed
        )

    def _make_env_runners(self, creator, n: int, seed_base: int) -> List[Any]:
        import ray_tpu
        from ray_tpu.rllib.env.env_runner import EnvRunner

        config = self.config
        runner_cls = ray_tpu.remote(EnvRunner)
        return [
            runner_cls.options(num_cpus=1).remote(
                creator,
                self.module,
                num_envs=config.num_envs_per_runner,
                rollout_length=config.rollout_fragment_length,
                seed=seed_base + 1000 * (i + 1),
                gamma=config.gamma,
                record_final_obs=self._record_final_obs,
                record_value_extras=self._record_value_extras,
                obs_connector=config.env_to_module_connector,
                action_connector=config.module_to_env_connector,
                exploration=config.exploration_config,
                default_explore=config.explore,
                callbacks=config.callbacks_class,
            )
            for i in range(n)
        ]

    def exploration_push(self, env_steps: int):
        """What to push to runners this iteration: the configured strategy's
        schedule dict, or None when there is nothing to anneal."""
        if self.exploration is None:
            return None
        sched = self.exploration.schedule(env_steps)
        return sched or None

    # ------------------------------------------------------------- multi-agent
    # Whether this algorithm supports policy maps (PPO opts in; see
    # `_supports_multi_agent` checks below). Reference: every algorithm rides
    # the same policy-map machinery; here MA support is per-algorithm.
    _supports_multi_agent = False
    # Offline algorithms (MARWIL/BC) set False: no sampling actors are built.
    _needs_env_runners = True

    def _init_multi_agent(self, creator) -> None:
        import gymnasium as gym

        import ray_tpu
        from ray_tpu.rllib.core.learner_group import LearnerGroup
        from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner

        config = self.config
        if not self._supports_multi_agent:
            raise ValueError(
                f"{type(self).__name__} does not support multi-agent training"
            )
        if config.exploration_config is not None:
            # MultiAgentEnvRunner routes exploration through per-policy
            # module forwards (epsilon push only); silently ignoring a
            # configured strategy would misreport what trained.
            raise ValueError(
                "exploration_config strategies are single-agent only; "
                "multi-agent policies use their modules' built-in exploration"
            )
        mapping = config.policy_mapping_fn
        if mapping is None:
            if len(config.policies) != 1:
                raise ValueError(
                    "policy_mapping_fn is required with more than one policy"
                )
            only = next(iter(config.policies))
            mapping = lambda aid: only  # noqa: E731
            config.policy_mapping_fn = mapping
        probe = creator()
        try:
            obs_spaces, act_spaces = probe.observation_space, probe.action_space
            if not isinstance(obs_spaces, dict):
                raise ValueError(
                    "multi-agent training requires a MultiAgentEnv with dict "
                    "observation/action spaces (see make_multi_agent)"
                )
            # One representative agent per policy defines its module spec.
            # Every agent must map INTO the policy map — an unmapped agent
            # would die with a bare KeyError inside the runner actor later.
            agent_of: Dict[str, str] = {}
            for aid in obs_spaces:
                pid = mapping(aid)
                if pid not in config.policies:
                    raise ValueError(
                        f"policy_mapping_fn({aid!r}) -> {pid!r}, which is not "
                        f"in policies {sorted(config.policies)}"
                    )
                agent_of.setdefault(pid, aid)
            missing = set(config.policies) - set(agent_of)
            if missing:
                raise ValueError(
                    f"no agent maps to policies {sorted(missing)}; check "
                    "policy_mapping_fn against the env's agent ids"
                )
            self.modules: Dict[str, Any] = {}
            for pid, aid in agent_of.items():
                act_space = act_spaces[aid]
                obs_dim = int(np.prod(obs_spaces[aid].shape))
                if isinstance(act_space, gym.spaces.Discrete):
                    self.modules[pid] = self.make_module(obs_dim, int(act_space.n))
                elif isinstance(act_space, gym.spaces.Box):
                    self.modules[pid] = self.make_module_continuous(
                        obs_dim, act_space
                    )
                else:
                    raise NotImplementedError(
                        f"unsupported multi-agent action space {act_space}"
                    )
        finally:
            probe.close()
        self.module = None
        self.learner_group = None
        self.learner_groups: Dict[str, LearnerGroup] = {
            pid: LearnerGroup(
                mod,
                self.make_loss(),
                num_learners=config.num_learners,
                learning_rate=config.lr,
                optimizer=self.make_optimizer(),
                seed=config.seed + 31 * i,
                extra_update_fn=self.make_extra_update(),
            )
            for i, (pid, mod) in enumerate(self.modules.items())
        }
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.env_runners = [
            runner_cls.options(num_cpus=1).remote(
                creator,
                self.modules,
                mapping,
                num_envs=config.num_envs_per_runner,
                rollout_length=config.rollout_fragment_length,
                seed=config.seed + 1000 * (i + 1),
                gamma=config.gamma,
                lambda_=getattr(config, "lambda_", 0.95),
                default_explore=config.explore,
                callbacks=config.callbacks_class,
            )
            for i in range(config.num_env_runners)
        ]

    @property
    def is_multi_agent(self) -> bool:
        return self.config.is_multi_agent

    # -------------------------------------------------------------- interface
    # What the base module kind is for Discrete action spaces; value-based
    # algorithms (DQN) override to "q". Routed through the ModelCatalog so
    # `config.model` (hiddens/activation/custom_module) drives architecture
    # (reference: `rllib/models/catalog.py:197`).
    _module_kind = "pi_vf"

    def make_module(self, obs_dim: int, num_actions: int):
        """The RLModule for this algorithm, built by the catalog from
        `config.model`."""
        import gymnasium as gym

        from ray_tpu.rllib.models.catalog import ModelCatalog

        return ModelCatalog.get_module(
            self._module_kind, obs_dim, gym.spaces.Discrete(num_actions),
            self.config.model,
        )

    def make_module_continuous(self, obs_dim: int, act_space):
        """RLModule for Box action spaces (continuous-control algorithms
        override, e.g. SAC's squashed-Gaussian actor + twin critics)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support continuous action spaces"
        )

    def make_loss(self) -> Callable:
        raise NotImplementedError

    def make_optimizer(self):
        """Optional optax transform; None -> LearnerGroup's default adam(lr)."""
        return None

    def make_extra_update(self) -> Optional[Callable]:
        """Optional pure (new_params, extra) -> new_extra applied inside the
        jitted learner step (e.g. SAC's polyak target blend)."""
        return None

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def collect_episode_metrics(self, out: Dict[str, Any]) -> Dict[str, Any]:
        """Fetch per-runner episode stats and fold the episode-weighted means
        into `out` (shared by every algorithm's training_step)."""
        import ray_tpu

        stats = ray_tpu.get([r.episode_stats.remote() for r in self.env_runners])
        episodes = [s for s in stats if s.get("episodes", 0) > 0]
        if episodes:
            weights = [s["episodes"] for s in episodes]
            out["episode_return_mean"] = float(
                np.average([s["episode_return_mean"] for s in episodes], weights=weights)
            )
            if all("episode_len_mean" in s for s in episodes):
                out["episode_len_mean"] = float(
                    np.average([s["episode_len_mean"] for s in episodes], weights=weights)
                )
            out["episodes_this_iter"] = int(sum(weights))
        return out

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        t0 = time.time()
        self.iteration += 1
        # Annealed strategy state (epsilon/scale/pure_random) is pushed to
        # EVERY algorithm's runners here — training_step implementations
        # don't each re-wire the schedule plumbing. One-iteration lag on
        # env_steps is inherent (steps count after sampling) and matches the
        # reference's global-timestep-based schedule reads.
        push = self.exploration_push(self.env_steps)
        if push is not None and self.env_runners:
            ray_tpu.get(
                [r.set_exploration.remote(push) for r in self.env_runners]
            )
        steps_before = self.env_steps
        metrics = self.training_step()
        if self.env_steps == steps_before:
            # Replay algorithms advance env_steps themselves (and report the
            # cumulative total as the metric); everyone else reports the
            # per-iteration count — fold it into the schedule counter here.
            self.env_steps = steps_before + int(
                metrics.get("num_env_steps_sampled") or 0
            )
        if push is not None:
            metrics.update(
                {f"exploration/{k}": float(np.asarray(v)) for k, v in push.items()}
            )
        cfg = self.config
        if (
            cfg.evaluation_interval
            and self.iteration % cfg.evaluation_interval == 0
        ):
            metrics["evaluation"] = self.evaluate()["evaluation"]
        metrics["training_iteration"] = self.iteration
        metrics["time_this_iter_s"] = time.time() - t0
        self.callbacks.on_train_result(algorithm=self, result=metrics)
        return metrics

    # ------------------------------------------------------------- evaluation
    def _ensure_eval_runners(self) -> List[Any]:
        """Dedicated eval-runner fleet, built lazily on first evaluate()
        (reference: `Algorithm.evaluate` + `evaluation_num_env_runners` —
        evaluation never samples through the training runners)."""
        if getattr(self, "_eval_runners", None):
            return self._eval_runners
        import ray_tpu
        from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner

        config = self.config
        creator = config.env_creator()
        n = max(1, config.evaluation_num_env_runners)
        if self.is_multi_agent:
            runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
            self._eval_runners = [
                runner_cls.options(num_cpus=1).remote(
                    creator,
                    self.modules,
                    config.policy_mapping_fn,
                    num_envs=config.num_envs_per_runner,
                    rollout_length=config.rollout_fragment_length,
                    seed=config.seed + 555_000 + 1000 * i,
                    gamma=config.gamma,
                    lambda_=getattr(config, "lambda_", 0.95),
                    callbacks=config.callbacks_class,
                )
                for i in range(n)
            ]
        else:
            self._eval_runners = self._make_env_runners(
                creator, n, seed_base=config.seed + 555_000
            )
        return self._eval_runners

    def evaluate(self) -> Dict[str, Any]:
        """Run a dedicated evaluation pass and return {"evaluation": metrics}
        (reference: `rllib/algorithms/algorithm.py:847 def evaluate`).
        Samples `evaluation_duration` episodes (or timesteps) on the eval
        fleet with `evaluation_explore` (deterministic by default), entirely
        separate from training rollouts."""
        import ray_tpu

        cfg = self.config
        self.callbacks.on_evaluate_start(algorithm=self)
        runners = self._ensure_eval_runners()
        if self.is_multi_agent:
            weights = {
                pid: lg.get_weights() for pid, lg in self.learner_groups.items()
            }
        else:
            weights = self.learner_group.get_weights()
        sync = [r.set_weights.remote(weights) for r in runners]
        # Exploration schedules live in the driver: push the current annealed
        # value so evaluation_explore=True measures the schedule's policy, not
        # a fresh runner's initial-state default (epsilon=1.0 / scale=1.0).
        if cfg.evaluation_explore:
            if self.exploration is not None:
                push = self.exploration_push(self.env_steps)
                if push is not None:
                    sync += [r.set_exploration.remote(push) for r in runners]
            elif callable(getattr(self, "epsilon", None)):
                sync += [r.set_exploration.remote(self.epsilon()) for r in runners]
        # Eval runners adopt the training runners' connector state, frozen,
        # so normalization matches training without polluting its stats.
        if not self.is_multi_agent and self.env_runners and cfg.env_to_module_connector:
            state = ray_tpu.get(self.env_runners[0].get_connector_state.remote())
            sync += [
                r.set_connector_state.remote(state, freeze=True) for r in runners
            ]
        ray_tpu.get(sync)
        # Drop episodes left over from a previous evaluate() round.
        ray_tpu.get([r.episode_stats.remote(clear=True) for r in runners])

        episodes = 0
        steps = 0
        ret_sum = 0.0
        len_sum = 0.0
        ret_min, ret_max = float("inf"), float("-inf")
        target = max(1, cfg.evaluation_duration)
        by_episodes = cfg.evaluation_duration_unit == "episodes"
        rounds = 0
        while True:
            rounds += 1
            samples = ray_tpu.get(
                [r.sample.remote(explore=cfg.evaluation_explore) for r in runners]
            )
            stats = ray_tpu.get([r.episode_stats.remote(clear=True) for r in runners])
            for ro in samples:
                if "rewards" in ro and not isinstance(ro.get("rewards"), dict):
                    steps += int(np.asarray(ro["rewards"]).size)
                else:
                    # Multi-agent: per-policy column dicts. PG maps carry
                    # advantages; replay maps carry rewards — count whichever
                    # exists.
                    steps += sum(
                        int(
                            np.asarray(
                                cols["rewards"] if "rewards" in cols
                                else cols["advantages"]
                            ).size
                        )
                        for cols in ro.values()
                    )
            for s in stats:
                n = int(s.get("episodes", 0))
                if n:
                    episodes += n
                    ret_sum += s["episode_return_mean"] * n
                    len_sum += s.get("episode_len_mean", 0.0) * n
                    ret_min = min(ret_min, s.get("episode_return_min", s["episode_return_mean"]))
                    ret_max = max(ret_max, s.get("episode_return_max", s["episode_return_mean"]))
            if by_episodes:
                if episodes >= target:
                    break
            elif steps >= target:
                break
            if rounds >= 100:
                # A degenerate env that never finishes an episode must not
                # hang evaluation forever.
                break
        metrics: Dict[str, Any] = {
            "num_episodes": episodes,
            "num_env_steps_sampled": steps,
        }
        if episodes:
            metrics["episode_return_mean"] = ret_sum / episodes
            metrics["episode_len_mean"] = len_sum / episodes
            metrics["episode_return_min"] = ret_min
            metrics["episode_return_max"] = ret_max
        out = {"evaluation": metrics}
        self.callbacks.on_evaluate_end(algorithm=self, evaluation_metrics=out)
        return out

    # ------------------------------------------------------------ checkpoints
    def _extra_state(self) -> Dict[str, Any]:
        """Algorithm-specific state beyond learner weights (e.g. PPO kl_coeff)."""
        return {}

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        pass

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        if self.is_multi_agent:
            learner_state = {
                pid: lg.state() for pid, lg in self.learner_groups.items()
            }
        else:
            learner_state = self.learner_group.state()
        with open(os.path.join(path, "algo_state.pkl"), "wb") as fh:
            pickle.dump(
                {
                    "iteration": self.iteration,
                    "learner": learner_state,
                    "extra": self._extra_state(),
                },
                fh,
            )
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "algo_state.pkl"), "rb") as fh:
            state = pickle.load(fh)
        self.iteration = state["iteration"]
        if self.is_multi_agent:
            for pid, s in state["learner"].items():
                self.learner_groups[pid].load_state(s)
        else:
            self.learner_group.load_state(state["learner"])
        self._load_extra_state(state.get("extra", {}))

    def stop(self) -> None:
        import ray_tpu

        for r in list(self.env_runners) + list(getattr(self, "_eval_runners", [])):
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
