"""BC: Behavioral Cloning — MARWIL with beta forced to 0.

Reference: `rllib/algorithms/bc/bc.py` — `BCConfig(MARWILConfig)` pins
`beta = 0.0` (no advantage weighting, no value loss; the loss degenerates to
-mean log pi(a|s) over the offline batch) and `validate()` rejects any other
beta.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig


class BCConfig(MARWILConfig):
    def __init__(self):
        super().__init__()
        self.beta = 0.0
        self.lr = 1e-3
        self._algo_cls = BC

    def training(self, **kwargs) -> "BCConfig":
        super().training(**kwargs)
        if self.beta != 0.0:
            raise ValueError("For behavioral cloning, `beta` must be 0.0")
        return self


class BC(MARWIL):
    pass
