"""A2C: synchronous advantage actor-critic.

Reference: `rllib/algorithms/a2c/a2c.py` (A2CConfig — synchronous rollout
gather + one SGD step per iteration on the plain actor-critic loss;
`a3c_torch_policy.py` loss: -logp * advantage + vf_coeff * value_error -
entropy_coeff * entropy, with GAE advantages from postprocessing).

TPU-first: same jitted-single-update shape as PPO minus the surrogate
machinery — one gradient step per batch of gathered rollouts, GAE on the
host, the loss a pure function the learner jits with donated state.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import _flatten, compute_gae


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.lambda_ = 1.0  # reference A2C default: plain returns
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self._algo_cls = A2C


def make_a2c_loss(config: "A2CConfig") -> Callable:
    """Pure (module, params, batch) -> (loss, aux) for JaxLearner.jit."""
    vf_coeff = config.vf_loss_coeff
    ent_coeff = config.entropy_coeff

    def loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        adv = jax.lax.stop_gradient(batch["advantages"])
        pi_loss = -jnp.mean(logp * adv)
        vf_loss = jnp.mean(jnp.square(values - batch["value_targets"]))
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    return loss


class A2C(Algorithm):
    # Like PPO: truncations bootstrap through runner-side values.
    _record_final_obs = False

    def make_loss(self) -> Callable:
        return make_a2c_loss(self.config)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.config
        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        flats: List[Dict[str, np.ndarray]] = []
        for ro in rollouts:
            ro = dict(ro)
            ro.update(compute_gae(ro, cfg.gamma, cfg.lambda_))
            flats.append(_flatten(ro))
        keys = ("obs", "actions", "advantages", "value_targets")
        batch = {k: np.concatenate([f[k] for f in flats]) for k in keys}
        a = batch["advantages"]
        batch["advantages"] = (a - a.mean()) / max(1e-4, a.std())
        out = dict(self.learner_group.update(batch))
        out["num_env_steps_sampled"] = len(batch["advantages"])
        return self.collect_episode_metrics(out)
