"""SAC: soft actor-critic with twin critics, auto-tuned temperature, and
polyak-averaged target networks — the continuous-control algorithm of the zoo.

Reference: `rllib/algorithms/sac/sac.py` (SACConfig: `twin_q=True, tau=5e-3,
initial_alpha=1.0, target_entropy="auto" -> -act_dim, n_step=1`) and the loss
in `sac_torch_policy.py` (critic: huber/mse on Q - y with
y = r + gamma * (min twin target Q - alpha * logp(a'|s')); actor:
alpha * logp(a|s) - min Q(s, a) with reparameterized a; alpha:
-log_alpha * (logp + target_entropy)).

TPU-first shape: all three objectives (critic, actor, temperature) are ONE
pure jitted loss over a single params pytree, with stop-gradients carving the
per-objective dependency structure the reference expresses through three
separate optimizers; the polyak target blend runs INSIDE the jitted step via
JaxLearner's extra_update_fn, so target state never round-trips to the host.
Policy noise is pre-drawn on the host and rides in the batch, keeping the
loss pure (no RNG threading through the learner)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import DQN, ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 5e-3
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        self.train_batch_size = 256
        self.updates_per_iteration = 64
        self.target_entropy: Optional[float] = None  # None -> -act_dim
        self.grad_clip = 10.0
        self.model = {"hiddens": (256, 256)}
        self._algo_cls = SAC


def make_sac_loss(config: SACConfig, target_entropy: float) -> Callable:
    gamma = config.gamma

    def loss(module, params, batch, extra):
        import jax
        import jax.numpy as jnp

        sg = jax.lax.stop_gradient
        alpha = jnp.exp(params["log_alpha"])

        # --- critic: y from target twins and a fresh next action ------------
        a_next, logp_next = module.sample(params, batch["next_obs"], batch["noise_next"])
        q1t = module.q_values(extra["q1"], batch["next_obs"], a_next)
        q2t = module.q_values(extra["q2"], batch["next_obs"], a_next)
        y = sg(
            batch["rewards"]
            + gamma
            * (1.0 - batch["terminateds"])
            * (jnp.minimum(q1t, q2t) - alpha * logp_next)
        )
        q1 = module.q_values(params["q1"], batch["obs"], batch["actions"])
        q2 = module.q_values(params["q2"], batch["obs"], batch["actions"])
        # loss_weight zeroes rows whose TD target is invalid (a truncated
        # tail with no recorded final obs — the multi-agent runner emits
        # these); the actor/alpha terms keep them, their states are real.
        if "loss_weight" in batch:
            w = batch["loss_weight"]
            denom = jnp.maximum(jnp.sum(w), 1.0)
            critic_loss = (
                jnp.sum(w * jnp.square(q1 - y)) / denom
                + jnp.sum(w * jnp.square(q2 - y)) / denom
            )
        else:
            critic_loss = jnp.mean(jnp.square(q1 - y)) + jnp.mean(jnp.square(q2 - y))

        # --- actor: reparameterized a through frozen critics ----------------
        a_pi, logp_pi = module.sample(params, batch["obs"], batch["noise_pi"])
        q_pi = jnp.minimum(
            module.q_values(sg(params["q1"]), batch["obs"], a_pi),
            module.q_values(sg(params["q2"]), batch["obs"], a_pi),
        )
        actor_loss = jnp.mean(sg(alpha) * logp_pi - q_pi)

        # --- temperature -----------------------------------------------------
        alpha_loss = -jnp.mean(
            params["log_alpha"] * sg(logp_pi + target_entropy)
        )

        total = critic_loss + actor_loss + alpha_loss
        aux = {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha_loss": alpha_loss,
            "alpha": alpha,
            "q_mean": jnp.mean(q1),
            "logp_pi_mean": jnp.mean(logp_pi),
        }
        return total, aux

    return loss


class SAC(Algorithm):
    # Policy-map training via MultiAgentEnvRunner's replay mode (continuous
    # Box agents; per-policy buffers/targets).
    _supports_multi_agent = True

    def __init__(self, config: SACConfig):
        super().__init__(config)
        self.num_updates = 0
        self.env_steps = 0
        self._rng = np.random.default_rng(config.seed)
        # Target twins start as copies of the online critics.
        if self.is_multi_agent:
            self.buffers = {
                pid: ReplayBuffer(config.buffer_capacity) for pid in self.modules
            }
            for lg in self.learner_groups.values():
                w = lg.get_weights()
                lg.set_extra({"q1": w["q1"], "q2": w["q2"]})
        else:
            self.buffer = ReplayBuffer(config.buffer_capacity)
            w = self.learner_group.get_weights()
            self.learner_group.set_extra({"q1": w["q1"], "q2": w["q2"]})

    def make_module_continuous(self, obs_dim: int, act_space):
        from ray_tpu.rllib.models.catalog import ModelCatalog

        # Multi-agent note: make_loss() reads the LAST value set here; with
        # heterogeneous Box shapes across policies, pass an explicit
        # config.target_entropy.
        self._target_entropy = (
            self.config.target_entropy
            if self.config.target_entropy is not None
            else -float(np.prod(act_space.shape))
        )
        return ModelCatalog.get_module(
            "squashed_gaussian", obs_dim, act_space, self.config.model
        )

    def make_module(self, obs_dim: int, num_actions: int):
        raise NotImplementedError(
            "SAC in this build targets continuous (Box) action spaces"
        )

    def make_loss(self) -> Callable:
        return make_sac_loss(self.config, self._target_entropy)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    def make_extra_update(self) -> Callable:
        tau = self.config.tau

        def polyak(new_params, extra):
            import jax

            online = {"q1": new_params["q1"], "q2": new_params["q2"]}
            return jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o, extra, online
            )

        return polyak

    # ----------------------------------------------------------- one iteration
    def _training_step_multi_agent(self) -> Dict[str, Any]:
        from ray_tpu.rllib.algorithms.dqn import replay_ma_training_step

        def add_noise(pid: str, batch: Dict[str, np.ndarray]) -> None:
            act_dim = self.modules[pid].act_dim
            B = len(batch["rewards"])
            batch["noise_next"] = self._rng.standard_normal(
                (B, act_dim)
            ).astype(np.float32)
            batch["noise_pi"] = self._rng.standard_normal(
                (B, act_dim)
            ).astype(np.float32)

        return replay_ma_training_step(self, batch_extras=add_noise)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        if self.is_multi_agent:
            return self._training_step_multi_agent()
        cfg = self.config
        weights = self.learner_group.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.env_runners])
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        for ro in rollouts:
            self.buffer.add(DQN._transitions(ro))
            self.env_steps += int(ro["rewards"].size)

        out: Dict[str, Any] = {
            "buffer_size": self.buffer.size,
            "num_env_steps_sampled": self.env_steps,
        }
        act_dim = self.module.act_dim
        if self.buffer.size >= cfg.learning_starts:
            metrics_acc: List[Dict[str, float]] = []
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                B = len(batch["rewards"])
                batch["noise_next"] = self._rng.standard_normal(
                    (B, act_dim)
                ).astype(np.float32)
                batch["noise_pi"] = self._rng.standard_normal(
                    (B, act_dim)
                ).astype(np.float32)
                metrics_acc.append(self.learner_group.update(batch))
                self.num_updates += 1
            out.update(
                {k: float(np.mean([m[k] for m in metrics_acc])) for k in metrics_acc[0]}
            )
        return self.collect_episode_metrics(out)

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        import jax

        if self.is_multi_agent:
            targets = {
                pid: jax.tree.map(lambda x: np.asarray(x), lg.get_extra())
                for pid, lg in self.learner_groups.items()
            }
        else:
            targets = jax.tree.map(
                lambda x: np.asarray(x), self.learner_group.get_extra()
            )
        return {
            "targets": targets,
            "num_updates": self.num_updates,
            "env_steps": self.env_steps,
        }

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        if state.get("targets") is not None:
            if self.is_multi_agent:
                for pid, lg in self.learner_groups.items():
                    lg.set_extra(state["targets"][pid])
            else:
                self.learner_group.set_extra(state["targets"])
        self.num_updates = int(state.get("num_updates", 0))
        self.env_steps = int(state.get("env_steps", 0))
