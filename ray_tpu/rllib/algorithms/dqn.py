"""DQN: deep Q-learning with replay, target network, and double-Q targets.

Reference: `rllib/algorithms/dqn/dqn.py` (DQNConfig: replay buffer,
`target_network_update_freq`, `n_step`, double-Q default) and the TD loss in
`dqn_torch_policy.py` (huber on Q(s,a) - y, y = r + gamma^n * Q_target).

TPU-first shape: the TD loss is one pure jitted function on the JaxLearner
stack (same learner/LearnerGroup machinery as PPO); the target network's
parameters are the learner's replicated EXTRA state (`set_extra`) — never in
the batch, which shards over data and slices per remote learner — so target
syncs neither trigger recompilation nor collide with batch sharding. The
replay buffer is host-side numpy in the driver — random uniform sampling is
memory bookkeeping, not MXU work. Exploration is epsilon-greedy with the
schedule held by the driver and pushed to runners as a traced scalar.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_capacity = 50_000
        self.learning_starts = 1_000
        self.train_batch_size = 64
        self.updates_per_iteration = 32
        self.target_network_update_freq = 200  # in learner updates
        self.double_q = True
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 10_000  # env steps
        self.grad_clip = 10.0
        # Rainbow knobs (reference DQNConfig: `n_step`, `num_atoms`,
        # `v_min/v_max`, `dueling` — Rainbow is DQN configuration, not a
        # separate algorithm). n_step > 1 builds n-step returns with per-row
        # bootstrap discounts; num_atoms > 1 switches to the C51 categorical
        # distributional loss on a DistributionalQModule.
        self.n_step = 1
        self.num_atoms = 1
        self.v_min = -10.0
        self.v_max = 10.0
        self.dueling = False
        # None -> uniform ring buffer; {"type": "PrioritizedReplayBuffer",
        # "alpha": .., "beta": ..} -> proportional prioritization with IS
        # weights riding `loss_weight` (reference: DQNConfig
        # `replay_buffer_config`, default MultiAgentPrioritizedReplayBuffer).
        self.replay_buffer_config: Optional[Dict[str, Any]] = None
        self._algo_cls = DQN

    def replay_is_prioritized(self) -> bool:
        rbc = self.replay_buffer_config or {}
        return rbc.get("type") in ("PrioritizedReplayBuffer", PrioritizedReplayBuffer)

    def make_replay_buffer(self) -> ReplayBuffer:
        rbc = self.replay_buffer_config
        if rbc:
            typ = rbc.get("type", "ReplayBuffer")
            if self.replay_is_prioritized():
                return PrioritizedReplayBuffer(
                    self.buffer_capacity, alpha=rbc.get("alpha", 0.6)
                )
            if typ not in ("ReplayBuffer", ReplayBuffer):
                raise ValueError(f"unknown replay buffer type {typ!r}")
        return ReplayBuffer(self.buffer_capacity)

    def training(self, **kwargs) -> "DQNConfig":
        aliases = {"target_update_freq": "target_network_update_freq"}
        kwargs = {aliases.get(k, k): v for k, v in kwargs.items()}
        super().training(**kwargs)
        return self


def make_dqn_loss(config: DQNConfig) -> Callable:
    """Pure (module, params, batch, extra) -> (loss, aux): huber TD error with
    (double-)Q targets from the target params in the learner's extra state."""
    gamma = config.gamma
    double_q = config.double_q

    def loss(module, params, batch, extra):
        import jax.numpy as jnp

        target_params = extra["target_params"]
        q_all, _ = module.forward(params, batch["obs"])
        q_sa = jnp.take_along_axis(q_all, batch["actions"][..., None], axis=-1)[..., 0]

        tq_all, _ = module.forward(target_params, batch["next_obs"])
        if double_q:
            # Online net picks the action, target net evaluates it.
            next_q_online, _ = module.forward(params, batch["next_obs"])
            a_star = jnp.argmax(next_q_online, axis=-1)
            tq = jnp.take_along_axis(tq_all, a_star[..., None], axis=-1)[..., 0]
        else:
            tq = tq_all.max(axis=-1)
        # n-step batches carry a per-row bootstrap discount (gamma^h, h the
        # realized horizon — fragment tails have h < n); 1-step batches fall
        # back to the scalar. Dict membership is trace-time static.
        disc = batch["discount"] if "discount" in batch else gamma
        y = batch["rewards"] + disc * (1.0 - batch["terminateds"]) * tq
        y = jnp.asarray(y, jnp.float32)
        td = q_sa - y
        # loss_weight is all-ones when the runner recorded true final
        # observations (truncated rows bootstrap through them); the legacy
        # fallback in _transitions zero-weights truncated rows instead.
        weight = batch["loss_weight"]
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td, jnp.abs(td) - 0.5)
        total = jnp.sum(weight * huber) / jnp.maximum(jnp.sum(weight), 1.0)
        aux = {
            "td_error_mean": jnp.sum(weight * jnp.abs(td)) / jnp.maximum(jnp.sum(weight), 1.0),
            "q_mean": jnp.mean(q_sa),
            # Per-sample |TD| rides out of the SAME jitted update (the
            # learner passes vector aux through): prioritized replay
            # refreshes priorities from it instead of re-fetching weights and
            # running a second TD forward per gradient step.
            "td_abs": jnp.abs(td),
        }
        return total, aux

    return loss


def make_c51_loss(config: DQNConfig) -> Callable:
    """Categorical distributional TD loss (C51, Bellemare et al. 2017;
    reference: `dqn_torch_policy.py` num_atoms>1 branch). The Bellman-updated
    support Tz = r + gamma^h * (1-term) * z is projected onto the fixed atom
    grid and trained by cross-entropy against the online log-probs of the
    taken action; double-DQN selects the target action by online Q means.
    Projection is one-hot einsum — scatter-free, fuses on the MXU path."""
    gamma = config.gamma
    double_q = config.double_q

    def loss(module, params, batch, extra):
        import jax
        import jax.numpy as jnp

        natoms = module.num_atoms
        support = jnp.asarray(module.support)
        delta = (module.v_max - module.v_min) / (natoms - 1)

        logits = module.dist_logits(params, batch["obs"])  # (B, A, K)
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        act = batch["actions"][..., None, None]
        logp_sa = jnp.take_along_axis(
            logp_all, jnp.broadcast_to(act, act.shape[:-2] + (1, natoms)), axis=-2
        )[..., 0, :]  # (B, K)

        tprobs = module.dist_probs(extra["target_params"], batch["next_obs"])
        if double_q:
            q_next, _ = module.forward(params, batch["next_obs"])
        else:
            q_next = jnp.sum(tprobs * support, axis=-1)
        a_star = jnp.argmax(q_next, axis=-1)[..., None, None]
        p_next = jnp.take_along_axis(
            tprobs, jnp.broadcast_to(a_star, a_star.shape[:-2] + (1, natoms)),
            axis=-2,
        )[..., 0, :]  # (B, K)

        disc = batch["discount"][..., None] if "discount" in batch else gamma
        Tz = jnp.clip(
            batch["rewards"][..., None]
            + disc * (1.0 - batch["terminateds"])[..., None] * support,
            module.v_min,
            module.v_max,
        )
        b = (Tz - module.v_min) / delta
        lo = jnp.clip(jnp.floor(b), 0, natoms - 1)
        hi = jnp.clip(lo + 1, 0, natoms - 1)
        w_hi = b - lo  # 0 when b sits on an atom (incl. the top atom: hi==lo)
        w_lo = 1.0 - w_hi
        lo_i = lo.astype(jnp.int32)
        hi_i = hi.astype(jnp.int32)
        m = jnp.einsum(
            "bj,bjk->bk", p_next * w_lo, jax.nn.one_hot(lo_i, natoms)
        ) + jnp.einsum("bj,bjk->bk", p_next * w_hi, jax.nn.one_hot(hi_i, natoms))
        m = jax.lax.stop_gradient(m)

        ce = -jnp.sum(m * logp_sa, axis=-1)  # (B,)
        weight = batch["loss_weight"]
        total = jnp.sum(weight * ce) / jnp.maximum(jnp.sum(weight), 1.0)
        # Q(s,a) for metrics from the ALREADY-computed logits (no second
        # trunk forward): E_z[softmax] of the taken action's atom row.
        q_sa = jnp.sum(jnp.exp(logp_sa) * support, axis=-1)
        aux = {
            "td_error_mean": total,
            "q_mean": jnp.mean(q_sa),
            # Per-sample cross-entropy vs the projected target: the
            # distributional TD error (what the reference uses for
            # prioritized replay when num_atoms > 1), returned from the same
            # jitted update so priorities refresh without a second forward.
            "td_abs": ce,
        }
        return total, aux

    return loss


def n_step_columns(rew, dones, n: int, gamma: float):
    """Vectorized n-step window math over (T, N) rollout buffers.

    Returns (returns, end_index, discount): per row t the discounted reward
    sum over steps t..e (stopping at the first done or the fragment edge),
    the inclusive end index e, and the bootstrap discount gamma^(e-t+1).
    Loops over the n offsets only — O(n) vector ops, not O(T*N*n) Python.
    """
    T, N = rew.shape
    R = rew.astype(np.float32).copy()
    end = np.tile(np.arange(T, dtype=np.int64)[:, None], (1, N))
    discount = np.full((T, N), gamma, np.float32)
    cont = 1.0 - dones  # window may extend past step t+k-1
    for k in range(1, n):
        ext = cont[: T - k]  # rows that extend to step t+k
        R[: T - k] += (gamma**k) * rew[k:] * ext
        end[: T - k] = np.where(ext > 0, np.arange(k, T)[:, None], end[: T - k])
        discount[: T - k] = np.where(
            ext > 0, np.float32(gamma ** (k + 1)), discount[: T - k]
        )
        cont = cont.copy()
        cont[: T - k] *= 1.0 - dones[k:]
    return R, end, discount


def replay_ma_training_step(
    algo: Algorithm,
    *,
    exploration: Optional[float] = None,
    batch_extras: Optional[Callable[[str, Dict[str, np.ndarray]], None]] = None,
    after_update: Optional[Callable[[], None]] = None,
) -> Dict[str, Any]:
    """Shared multi-agent replay iteration for value-based algorithms
    (DQN, SAC): per-policy transition batches from the runners' replay mode
    feed per-policy buffers and learner updates. `exploration` pushes a
    driver-held schedule value (DQN epsilon); `batch_extras(pid, batch)`
    injects per-update columns (SAC noise); `after_update()` runs after each
    learner update (DQN target sync)."""
    import ray_tpu

    cfg = algo.config
    weights = {pid: lg.get_weights() for pid, lg in algo.learner_groups.items()}
    sync = [r.set_weights.remote(weights) for r in algo.env_runners]
    if exploration is not None:
        sync += [r.set_exploration.remote(exploration) for r in algo.env_runners]
    ray_tpu.get(sync)
    samples = ray_tpu.get([r.sample.remote() for r in algo.env_runners])
    for s in samples:
        for pid, cols in s.items():
            algo.buffers[pid].add(
                {
                    k: np.asarray(
                        v, None if k == "actions" else np.float32
                    )
                    for k, v in cols.items()
                }
            )
            algo.env_steps += int(np.asarray(cols["rewards"]).size)
    out: Dict[str, Any] = {"num_env_steps_sampled": algo.env_steps}
    if exploration is not None:
        out["epsilon"] = exploration
    train_set = cfg.policies_to_train or list(algo.learner_groups)
    for pid, lg in algo.learner_groups.items():
        buf = algo.buffers[pid]
        out[f"policy_{pid}/buffer_size"] = buf.size
        if pid not in train_set or buf.size < cfg.learning_starts:
            continue
        acc: List[Dict[str, float]] = []
        for _ in range(cfg.updates_per_iteration):
            batch = buf.sample(cfg.train_batch_size, algo._rng)
            if batch_extras is not None:
                batch_extras(pid, batch)
            m = lg.update(batch)
            m.pop("td_abs", None)  # vector aux; MA buffers are uniform
            acc.append(m)
            algo.num_updates += 1
            if after_update is not None:
                after_update()
        for k in acc[0]:
            out[f"policy_{pid}/{k}"] = float(np.mean([m[k] for m in acc]))
    return algo.collect_episode_metrics(out)


class DQN(Algorithm):
    # Policy-map training via MultiAgentEnvRunner's replay mode (per-policy
    # transition batches -> per-policy buffers/targets).
    _supports_multi_agent = True

    def __init__(self, config: DQNConfig):
        super().__init__(config)
        if self.is_multi_agent:
            if config.replay_is_prioritized():
                raise ValueError(
                    "prioritized replay is single-agent here; use uniform "
                    "buffers with multi-agent policy maps"
                )
            if config.n_step != 1 or config.num_atoms != 1 or config.dueling:
                # The MA path's transitions are built runner-side (1-step,
                # scalar Q); silently training different targets than
                # configured would misreport what trained.
                raise ValueError(
                    "n_step/num_atoms/dueling are single-agent DQN knobs; "
                    "multi-agent policy maps train 1-step scalar Q"
                )
            self.buffers = {
                pid: ReplayBuffer(config.buffer_capacity) for pid in self.modules
            }
        else:
            self.buffer = config.make_replay_buffer()
        self.num_updates = 0
        self.env_steps = 0
        self._rng = np.random.default_rng(config.seed)
        self._sync_target()

    def _sync_target(self) -> None:
        if self.is_multi_agent:
            self.target_params = {}
            for pid, lg in self.learner_groups.items():
                self.target_params[pid] = lg.get_weights()
                lg.set_extra({"target_params": self.target_params[pid]})
            return
        self.target_params = self.learner_group.get_weights()
        self.learner_group.set_extra({"target_params": self.target_params})

    # Q-network module from the catalog (epsilon-greedy exploration).
    _module_kind = "q"

    def make_module(self, obs_dim: int, num_actions: int):
        cfg = self.config
        if cfg.num_atoms > 1 or cfg.dueling:
            # Same model-dict conventions as the catalog path (fcnet_*
            # aliases honored); custom_module cannot combine with the
            # Rainbow architectures, so fail loudly instead of bypassing it.
            from ray_tpu.rllib.models.catalog import _activation, _hiddens

            m = cfg.model or {}
            if m.get("custom_module"):
                raise ValueError(
                    "custom_module cannot be combined with num_atoms>1/"
                    "dueling (those knobs select their own architectures)"
                )
            hiddens, activation = _hiddens(m), _activation(m)
            if cfg.num_atoms > 1:
                from ray_tpu.rllib.core.distributional import (
                    DistributionalQModule,
                )

                return DistributionalQModule(
                    obs_dim,
                    num_actions,
                    hiddens=hiddens,
                    activation=activation,
                    num_atoms=cfg.num_atoms,
                    v_min=cfg.v_min,
                    v_max=cfg.v_max,
                    dueling=cfg.dueling,
                )
            from ray_tpu.rllib.core.distributional import DuelingQMLPModule

            return DuelingQMLPModule(
                obs_dim, num_actions, hiddens=hiddens, activation=activation
            )
        return super().make_module(obs_dim, num_actions)

    def make_loss(self) -> Callable:
        if self.config.num_atoms > 1:
            return make_c51_loss(self.config)
        return make_dqn_loss(self.config)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    # -------------------------------------------------------------- schedule
    def epsilon(self) -> float:
        from ray_tpu.rllib.utils.exploration import _anneal

        cfg = self.config
        return _anneal(
            cfg.epsilon_initial, cfg.epsilon_final, cfg.epsilon_decay_steps,
            self.env_steps,
        )

    # ----------------------------------------------------------- one iteration
    def _training_step_multi_agent(self) -> Dict[str, Any]:
        def sync_on_schedule():
            if self.num_updates % self.config.target_network_update_freq == 0:
                self._sync_target()

        return replay_ma_training_step(
            self, exploration=self.epsilon(), after_update=sync_on_schedule
        )

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        if self.is_multi_agent:
            return self._training_step_multi_agent()
        cfg = self.config
        weights = self.learner_group.get_weights()
        sync = [r.set_weights.remote(weights) for r in self.env_runners]
        out: Dict[str, Any] = {}
        if self.exploration is None:
            # Built-in epsilon-greedy schedule; configured strategies are
            # pushed (and reported) by the base train() instead.
            eps = self.epsilon()
            sync += [r.set_exploration.remote(eps) for r in self.env_runners]
            out["epsilon"] = eps
        ray_tpu.get(sync)
        rollouts = ray_tpu.get([r.sample.remote() for r in self.env_runners])
        for ro in rollouts:
            self.buffer.add(self._transitions(ro, cfg.n_step, cfg.gamma))
            self.env_steps += int(ro["rewards"].size)

        out.update(
            buffer_size=self.buffer.size,
            num_env_steps_sampled=self.env_steps,
        )
        prioritized = isinstance(self.buffer, PrioritizedReplayBuffer)
        beta = (cfg.replay_buffer_config or {}).get("beta", 0.4)
        if self.buffer.size >= cfg.learning_starts:
            metrics_acc: List[Dict[str, float]] = []
            for _ in range(cfg.updates_per_iteration):
                if prioritized:
                    batch = self.buffer.sample(
                        cfg.train_batch_size, self._rng, beta=beta
                    )
                    idx = batch.pop("batch_indexes")
                else:
                    batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                m = self.learner_group.update(batch)
                td = m.pop("td_abs", None)
                metrics_acc.append(m)
                self.num_updates += 1
                if prioritized:
                    # Refresh sampled priorities from the per-sample |TD| the
                    # update itself returned — no weight re-fetch, no second
                    # TD forward per gradient step.
                    td = np.asarray(td)
                    self.buffer.update_priorities(idx[: len(td)], td)
                if self.num_updates % cfg.target_network_update_freq == 0:
                    self._sync_target()
            out.update(
                {k: float(np.mean([m[k] for m in metrics_acc])) for k in metrics_acc[0]}
            )
        return self.collect_episode_metrics(out)

    @staticmethod
    def _transitions(
        ro: Dict[str, np.ndarray], n_step: int = 1, gamma: float = 0.99
    ) -> Dict[str, np.ndarray]:
        """(T, N) rollout buffers -> flat (s, a, r, s', terminated, weight);
        n_step > 1 adds n-step returns + a per-row bootstrap `discount`."""
        obs, dones, terms = ro["obs"], ro["dones"], ro["terminateds"]
        next_obs = np.concatenate([obs[1:], ro["last_obs"][None]], axis=0)
        # SAME_STEP autoreset: the row after a done holds the reset obs, which
        # is the CORRECT s' only for rows that didn't end; terminated rows
        # never use s'. Truncated (time-limit) rows substitute the true final
        # observation the runner recorded and keep full weight — the TD target
        # bootstraps through the real state, nothing is discarded.
        truncated = ro.get("truncateds")
        final_obs = ro.get("final_obs")
        if truncated is None or final_obs is None:
            truncated = dones - terms
            weight = 1.0 - truncated  # no final obs recorded: exclude rows
        else:
            mask = truncated.reshape(
                truncated.shape + (1,) * (final_obs.ndim - truncated.ndim)
            )
            next_obs = np.where(mask > 0, final_obs, next_obs)
            weight = np.ones_like(dones)
        rewards = ro["rewards"]
        flat = lambda a: a.reshape((-1,) + a.shape[2:])  # noqa: E731
        out = {
            "obs": flat(obs).astype(np.float32),
            "actions": flat(ro["actions"]),
        }
        if n_step > 1:
            # Each row's window runs to its end index e (first done or the
            # fragment edge); bootstrap obs/terminal/weight are GATHERED from
            # row e, so truncation handling above applies transitively.
            R, end, discount = n_step_columns(rewards, dones, n_step, gamma)
            envi = np.arange(obs.shape[1])
            out.update(
                rewards=flat(R),
                next_obs=flat(next_obs[end, envi]).astype(np.float32),
                terminateds=flat(terms[end, envi]).astype(np.float32),
                loss_weight=flat(weight[end, envi]).astype(np.float32),
                discount=flat(discount),
            )
        else:
            out.update(
                rewards=flat(rewards).astype(np.float32),
                next_obs=flat(next_obs).astype(np.float32),
                terminateds=flat(terms).astype(np.float32),
                loss_weight=flat(weight).astype(np.float32),
            )
        return out

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        return {
            "target_params": self.target_params,
            "num_updates": self.num_updates,
            "env_steps": self.env_steps,
        }

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        if "target_params" in state:
            self.target_params = state["target_params"]
            if self.is_multi_agent:
                for pid, lg in self.learner_groups.items():
                    lg.set_extra({"target_params": self.target_params[pid]})
            else:
                self.learner_group.set_extra({"target_params": self.target_params})
        self.num_updates = int(state.get("num_updates", 0))
        self.env_steps = int(state.get("env_steps", 0))
