"""APPO: Asynchronous PPO — IMPALA's V-trace chassis + PPO's clipped
surrogate against a lagging target policy.

Reference: `rllib/algorithms/appo/appo.py:39` (APPOConfig(ImpalaConfig):
`clip_param=0.4, use_kl_loss=False, kl_coeff=1.0, kl_target=0.01, tau=1.0,
target_update_frequency=1`) and the loss in `appo_torch_policy.py:171-266`:
V-trace computed with the TARGET network as the target policy
(rho = pi_target/mu), `is_ratio = clamp(mu/pi_target, 0, 2)`,
`logp_ratio = is_ratio * pi/mu`, clipped surrogate, optional
KL(target || current), value loss vs the V-trace targets; target network
refreshed every `target_update_frequency` updates by a tau-blend
(`appo.py:117` "updated_param = tau * current + (1 - tau) * target").

TPU-first shape: same (N, T) env-major batches and in-loss `lax.scan`
V-trace as IMPALA; the target params ride as the learner's replicated
`extra` pytree so the whole loss stays one pure jitted SPMD program, and the
tau-blend is a host-triggered `set_extra` (no torch-style target_model
module copies).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np

from ray_tpu.rllib.algorithms.impala import Impala, ImpalaConfig


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.clip_param = 0.4
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        self.kl_target = 0.01
        self.tau = 1.0
        self.target_update_frequency = 1
        self._algo_cls = APPO


def make_appo_loss(config: APPOConfig) -> Callable:
    """Pure (module, params, batch, target_params) -> (loss, aux)."""
    gamma = config.gamma
    rho_bar = config.vtrace_clip_rho_threshold
    pg_rho_bar = config.vtrace_clip_pg_rho_threshold
    c_bar = config.vtrace_clip_c_threshold
    clip = config.clip_param
    vf_coeff = config.vf_loss_coeff
    ent_coeff = config.entropy_coeff
    use_kl = config.use_kl_loss

    def loss(module, params, batch, target_params):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]            # (N, T, obs)
        actions = batch["actions"]    # (N, T)
        behavior_logp = batch["logp"]
        rewards = batch["rewards"]
        terms = batch["terminateds"]
        dones = batch["dones"]
        truncs = batch["truncateds"]
        final_obs = batch["final_obs"]
        last_obs = batch["last_obs"]

        logits, values = module.forward(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        curr_logp = jnp.take_along_axis(logp_all, actions[..., None], axis=-1)[..., 0]
        # Old (lagging target) policy — gradients never flow into it.
        t_logits, _ = module.forward(jax.lax.stop_gradient(target_params), obs)
        t_logp_all = jax.nn.log_softmax(t_logits)
        old_logp = jnp.take_along_axis(t_logp_all, actions[..., None], axis=-1)[..., 0]

        _, last_values = module.forward(params, last_obs)
        _, fin_values = module.forward(params, final_obs)

        # V-trace with the target policy as pi (appo_torch_policy.py:208:
        # target_policy_logits = old_policy_behaviour_logits).
        rho = jnp.exp(old_logp - behavior_logp)
        clipped_rho = jnp.minimum(rho, rho_bar)
        c = jnp.minimum(rho, c_bar)

        next_values = jnp.concatenate([values[:, 1:], last_values[:, None]], axis=1)
        next_values = jnp.where(truncs > 0, fin_values, next_values)
        next_values = next_values * (1.0 - terms)
        delta = clipped_rho * (rewards + gamma * next_values - values)

        def scan_fn(acc, xs):
            delta_t, c_t, done_t = xs
            acc = delta_t + gamma * c_t * (1.0 - done_t) * acc
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            scan_fn,
            jnp.zeros(values.shape[0], values.dtype),
            (delta.T, c.T, dones.T),
            reverse=True,
        )
        vs = jax.lax.stop_gradient(vs_minus_v.T + values)

        vs_next = jnp.concatenate([vs[:, 1:], last_values[:, None]], axis=1)
        vs_next = jnp.where(truncs > 0, fin_values, vs_next)
        vs_next = vs_next * (1.0 - terms)
        pg_adv = jax.lax.stop_gradient(
            jnp.minimum(rho, pg_rho_bar) * (rewards + gamma * vs_next - values)
        )

        # PPO surrogate with the decoupled importance ratio
        # (appo_torch_policy.py:236-251).
        is_ratio = jnp.clip(jnp.exp(behavior_logp - old_logp), 0.0, 2.0)
        logp_ratio = is_ratio * jnp.exp(curr_logp - behavior_logp)
        surrogate = jnp.minimum(
            pg_adv * logp_ratio,
            pg_adv * jnp.clip(logp_ratio, 1.0 - clip, 1.0 + clip),
        )
        pi_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean(jnp.square(values - vs))
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        # KL(old_policy || current) (appo_torch_policy.py:201).
        kl = jnp.mean(
            jnp.sum(jnp.exp(t_logp_all) * (t_logp_all - logp_all), axis=-1)
        )
        total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
        if use_kl:
            total = total + jnp.mean(batch["kl_coeff"]) * kl
        aux = {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": kl,
            "mean_is_ratio": jnp.mean(is_ratio),
        }
        return total, aux

    return loss


class APPO(Impala):
    def __init__(self, config: APPOConfig):
        super().__init__(config)
        self.kl_coeff = float(config.kl_coeff)
        self._updates_since_target_sync = 0
        # Target network = initial weights (reference initializes the target
        # model as a copy of the model).
        self.learner_group.set_extra(self.learner_group.get_weights())

    def make_loss(self) -> Callable:
        return make_appo_loss(self.config)

    # ----------------------------------------------------------- one iteration
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self._sample_env_major_batch()
        N = batch["rewards"].shape[0]
        batch["kl_coeff"] = np.full(N, self.kl_coeff, np.float32)
        out = dict(self.learner_group.update(batch))

        # Adaptive KL (only meaningful when the KL term is in the loss).
        if cfg.use_kl_loss:
            if out["mean_kl"] > 2.0 * cfg.kl_target:
                self.kl_coeff *= 1.5
            elif out["mean_kl"] < 0.5 * cfg.kl_target:
                self.kl_coeff *= 0.5
            out["kl_coeff"] = self.kl_coeff

        # Lagging target refresh (appo.py:117 tau-blend), every
        # `target_update_frequency` updates.
        self._updates_since_target_sync += 1
        if self._updates_since_target_sync >= cfg.target_update_frequency:
            self._updates_since_target_sync = 0
            import jax

            current = self.learner_group.get_weights()
            target = self.learner_group.get_extra()
            tau = cfg.tau
            blended = jax.tree.map(
                lambda c, t: tau * np.asarray(c) + (1.0 - tau) * np.asarray(t),
                current,
                target,
            )
            self.learner_group.set_extra(blended)
            out["num_target_updates"] = 1

        out["num_env_steps_sampled"] = int(batch["rewards"].size)
        return self.collect_episode_metrics(out)

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        return {
            "kl_coeff": self.kl_coeff,
            "target_params": self.learner_group.get_extra(),
        }

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        self.kl_coeff = float(state.get("kl_coeff", self.config.kl_coeff))
        if state.get("target_params") is not None:
            self.learner_group.set_extra(state["target_params"])
