"""CQL: conservative Q-learning — offline continuous-control RL.

Reference: `rllib/algorithms/cql/cql.py` (CQLConfig over SAC:
`min_q_weight=5.0, bc_iters=20000, temperature=1.0, num_actions=10`,
offline-only input) and the loss in `cql_torch_policy.py` (SAC objectives +
the CQL(H) regularizer: logsumexp over Q at sampled actions minus Q at the
dataset action, pushing Q down on out-of-distribution actions so the policy
can't exploit extrapolation error — the reason vanilla SAC diverges offline).

TPU-first shape: one pure jitted loss = SAC critic/actor/temperature terms +
the conservative penalty. The penalty's action samples (uniform random and
fresh policy samples at s and s') are PRE-DRAWN on the host and ride the
batch as (B, R, act_dim) tensors, so the jitted program stays RNG-free and
shards over remote learners exactly like every other loss here. Q towers
evaluate the (B, R) sample fan with one broadcast matmul — MXU-friendly,
no python loop over samples.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac import SACConfig, make_sac_loss


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.min_q_weight = 5.0
        self.cql_num_actions = 4  # R samples per source (random/pi/pi')
        self.train_batch_size = 256
        self.updates_per_iteration = 16
        self.num_env_runners = 0
        self._algo_cls = CQL


def make_cql_loss(config: CQLConfig, target_entropy: float) -> Callable:
    sac_loss = make_sac_loss(config, target_entropy)
    min_q_weight = float(config.min_q_weight)

    def loss(module, params, batch, extra):
        import jax
        import jax.numpy as jnp

        total, aux = sac_loss(module, params, batch, extra)

        # --- conservative penalty (CQL(H)) ---------------------------------
        # Q over the sample fan: uniform-random actions plus fresh policy
        # samples at s and s', importance-corrected (uniform density for the
        # random fan, policy logp for the sampled fans — `cql_torch_policy`).
        B, R, act_dim = batch["cql_random_actions"].shape
        obs_fan = jnp.broadcast_to(
            batch["obs"][:, None, :], (B, R, batch["obs"].shape[-1])
        )
        next_fan = jnp.broadcast_to(
            batch["next_obs"][:, None, :], (B, R, batch["next_obs"].shape[-1])
        )
        a_rand = batch["cql_random_actions"]
        a_pi, logp_pi = module.sample(params, obs_fan, batch["cql_noise_pi"])
        a_next, logp_next = module.sample(params, next_fan, batch["cql_noise_next"])
        # log-density of uniform over the action box.
        log_unif = -float(np.sum(np.log(module.act_high - module.act_low + 1e-8)))
        sg = jax.lax.stop_gradient
        penalties = {}
        for tower in ("q1", "q2"):
            q_rand = module.q_values(params[tower], obs_fan, a_rand)
            q_pi = module.q_values(params[tower], obs_fan, sg(a_pi))
            q_next = module.q_values(params[tower], obs_fan, sg(a_next))
            cat = jnp.concatenate(
                [
                    q_rand - log_unif,
                    q_pi - sg(logp_pi),
                    q_next - sg(logp_next),
                ],
                axis=1,
            )
            lse = jax.scipy.special.logsumexp(cat, axis=1) - jnp.log(3.0 * R)
            q_data = module.q_values(params[tower], batch["obs"], batch["actions"])
            penalties[tower] = jnp.mean(lse - q_data)
        cql_term = min_q_weight * (penalties["q1"] + penalties["q2"])
        aux = dict(aux)
        aux["cql_penalty"] = (penalties["q1"] + penalties["q2"]) / 2.0
        return total + cql_term, aux

    return loss


class CQL(Algorithm):
    """Offline: batches come from `config.offline_data(input_=...)` with
    obs/actions/rewards/next_obs (or new_obs)/dones columns; no sampling
    actors are built. `evaluate()` (base Algorithm) rolls the learned policy
    in the config env with dedicated eval runners."""

    _needs_env_runners = False

    def __init__(self, config: CQLConfig):
        super().__init__(config)
        self.reader = config.build_input_reader(
            batch_size=config.train_batch_size, seed=config.seed
        )
        self.num_updates = 0
        self._rng = np.random.default_rng(config.seed)
        w = self.learner_group.get_weights()
        self.learner_group.set_extra({"q1": w["q1"], "q2": w["q2"]})

    def make_module_continuous(self, obs_dim: int, act_space):
        from ray_tpu.rllib.models.catalog import ModelCatalog

        self._target_entropy = (
            self.config.target_entropy
            if self.config.target_entropy is not None
            else -float(np.prod(act_space.shape))
        )
        return ModelCatalog.get_module(
            "squashed_gaussian", obs_dim, act_space, self.config.model
        )

    def make_module(self, obs_dim: int, num_actions: int):
        raise NotImplementedError("CQL targets continuous (Box) action spaces")

    def make_loss(self) -> Callable:
        return make_cql_loss(self.config, self._target_entropy)

    def make_optimizer(self):
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.grad_clip),
            optax.adam(self.config.lr),
        )

    def make_extra_update(self) -> Callable:
        tau = self.config.tau

        def polyak(new_params, extra):
            import jax

            online = {"q1": new_params["q1"], "q2": new_params["q2"]}
            return jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o, extra, online
            )

        return polyak

    # ----------------------------------------------------------- one iteration
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        act_dim = self.module.act_dim
        low, high = self.module.act_low, self.module.act_high
        R = int(cfg.cql_num_actions)
        metrics_acc: List[Dict[str, float]] = []
        for _ in range(max(1, cfg.updates_per_iteration)):
            raw = dict(self.reader.next())
            batch = self._prep_batch(raw, cfg.train_batch_size)
            B = len(batch["rewards"])
            batch["noise_next"] = self._rng.standard_normal(
                (B, act_dim)
            ).astype(np.float32)
            batch["noise_pi"] = self._rng.standard_normal(
                (B, act_dim)
            ).astype(np.float32)
            batch["cql_random_actions"] = self._rng.uniform(
                low, high, (B, R, act_dim)
            ).astype(np.float32)
            batch["cql_noise_pi"] = self._rng.standard_normal(
                (B, R, act_dim)
            ).astype(np.float32)
            batch["cql_noise_next"] = self._rng.standard_normal(
                (B, R, act_dim)
            ).astype(np.float32)
            metrics_acc.append(self.learner_group.update(batch))
            self.num_updates += 1
        out = {
            k: float(np.mean([m[k] for m in metrics_acc])) for k in metrics_acc[0]
        }
        out["num_updates"] = self.num_updates
        out["num_env_steps_trained"] = (
            max(1, cfg.updates_per_iteration) * cfg.train_batch_size
        )
        return out

    @staticmethod
    def _prep_batch(raw: Dict[str, np.ndarray], batch_size: int) -> Dict[str, np.ndarray]:
        next_obs = raw.get("next_obs", raw.get("new_obs"))
        if next_obs is None:
            raise ValueError(
                "CQL needs next_obs (or new_obs) in the offline data"
            )
        dones = raw.get("terminateds", raw.get("dones"))
        if dones is None:
            raise ValueError("CQL needs terminateds/dones in the offline data")
        batch = {
            "obs": np.asarray(raw["obs"], np.float32),
            "actions": np.asarray(raw["actions"], np.float32),
            "rewards": np.asarray(raw["rewards"], np.float32),
            "next_obs": np.asarray(next_obs, np.float32),
            "terminateds": np.asarray(dones, np.float32),
        }
        n = len(batch["rewards"])
        if n > batch_size:
            batch = {k: v[:batch_size] for k, v in batch.items()}
        return batch

    # -------------------------------------------------------------- checkpoint
    def _extra_state(self) -> Dict[str, Any]:
        import jax

        return {
            "targets": jax.tree.map(
                lambda x: np.asarray(x), self.learner_group.get_extra()
            ),
            "num_updates": self.num_updates,
        }

    def _load_extra_state(self, state: Dict[str, Any]) -> None:
        if state.get("targets") is not None:
            self.learner_group.set_extra(state["targets"])
        self.num_updates = int(state.get("num_updates", 0))
