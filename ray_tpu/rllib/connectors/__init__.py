from ray_tpu.rllib.connectors.connector import (
    Connector,
    ConnectorPipeline,
    build_connector,
)
from ray_tpu.rllib.connectors.env_to_module import (
    ClipObs,
    FlattenObs,
    NormalizeObs,
)
from ray_tpu.rllib.connectors.module_to_env import ClipActions, UnsquashActions

__all__ = [
    "Connector",
    "ConnectorPipeline",
    "build_connector",
    "FlattenObs",
    "ClipObs",
    "NormalizeObs",
    "ClipActions",
    "UnsquashActions",
]
