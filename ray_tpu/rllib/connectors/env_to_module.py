"""Observation connectors (env -> module seam).

Reference: `rllib/connectors/agent/*` — obs preprocessing that runs in the
runner before the policy forward: flattening, clipping, running-moment
normalization (`MeanStdFilter` in `rllib/utils/filter.py`).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.connectors.connector import Connector


class FlattenObs(Connector):
    """Ravel each observation row to 1-D float32 (dict/tensor obs -> MLP)."""

    def __call__(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, np.float32)
        return data.reshape(data.shape[0], -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = float(low), float(high)

    def __call__(self, data: np.ndarray) -> np.ndarray:
        return np.clip(data, self.low, self.high)

    def __repr__(self):
        return f"ClipObs({self.low}, {self.high})"


class NormalizeObs(Connector):
    """Running mean/std normalization (reference: `MeanStdFilter`,
    `rllib/utils/filter.py` — Welford accumulation). Stats update on every
    batch seen during exploration; `frozen` stops accumulation (evaluation
    uses the training stats without polluting them)."""

    def __init__(self, clip: float = 10.0, epsilon: float = 1e-8):
        self.clip = float(clip)
        self.epsilon = float(epsilon)
        self.count = 0.0
        self.mean: Any = None
        self.m2: Any = None
        self.frozen = False

    def __call__(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, np.float32)
        if not self.frozen:
            self._update(data)
        if self.count < 2:
            return data
        std = np.sqrt(self.m2 / max(self.count - 1, 1.0)) + self.epsilon
        return np.clip((data - self.mean) / std, -self.clip, self.clip)

    def _update(self, batch: np.ndarray) -> None:
        # Chan et al. parallel Welford merge of the batch's moments.
        n = float(len(batch))
        if n == 0:
            return
        b_mean = batch.mean(axis=0)
        b_m2 = ((batch - b_mean) ** 2).sum(axis=0)
        if self.mean is None:
            self.count, self.mean, self.m2 = n, b_mean, b_m2
            return
        delta = b_mean - self.mean
        tot = self.count + n
        self.mean = self.mean + delta * (n / tot)
        self.m2 = self.m2 + b_m2 + np.square(delta) * self.count * n / tot
        self.count = tot

    def state(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean": None if self.mean is None else self.mean.copy(),
            "m2": None if self.m2 is None else self.m2.copy(),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.count = state.get("count", 0.0)
        self.mean = state.get("mean")
        self.m2 = state.get("m2")

    def __repr__(self):
        return f"NormalizeObs(count={int(self.count)})"
