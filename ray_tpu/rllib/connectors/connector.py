"""Connectors: composable pre/post-processing between env and module.

Reference: `rllib/connectors/connector.py` (`Connector`, `ConnectorPipeline`)
— small, stateful-if-needed transforms chained into pipelines that sit on
the two seams of an EnvRunner: observations flowing env -> module, and
actions flowing module -> env. Keeping them outside the module keeps the
jitted policy forward pure; connectors run host-side numpy per step.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np


class Connector:
    """One transform. `__call__(data)` returns the transformed array; state()
    / set_state() carry whatever the transform accumulates (e.g. running
    normalization moments) through checkpoints and across weight syncs."""

    def __call__(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    def __repr__(self):
        return type(self).__name__


class ConnectorPipeline(Connector):
    """Apply connectors in order (reference: `ConnectorPipeline`)."""

    def __init__(self, *connectors: Connector):
        self.connectors: List[Connector] = list(connectors)

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def __call__(self, data: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            data = c(data)
        return data

    def state(self) -> Dict[str, Any]:
        return {str(i): c.state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.set_state(state[str(i)])

    def __repr__(self):
        return f"ConnectorPipeline({', '.join(map(repr, self.connectors))})"


def build_connector(spec) -> Connector:
    """Normalize a config value into a Connector: an instance passes through,
    a callable is invoked (factory), a list/tuple becomes a pipeline."""
    if spec is None:
        return None
    if isinstance(spec, Connector):
        return spec
    if isinstance(spec, (list, tuple)):
        return ConnectorPipeline(*[build_connector(s) for s in spec])
    if callable(spec):
        return build_connector(spec())
    raise TypeError(f"cannot build a connector from {spec!r}")
