"""Action connectors (module -> env seam).

Reference: `rllib/connectors/action/*` (`ClipActionsConnector`,
`NormalizeActionsConnector` / unsquash) — transforms applied to the module's
action before the env sees it. The training batch keeps the MODULE's action
(losses live in module action space); only the env receives the transform.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.connectors.connector import Connector


class ClipActions(Connector):
    """Clip module actions to the env's Box bounds (reference:
    `ClipActionsConnector`)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, data: np.ndarray) -> np.ndarray:
        return np.clip(data, self.low, self.high)

    def __repr__(self):
        return "ClipActions"


class UnsquashActions(Connector):
    """Affine-map module actions from (-1, 1) onto the env's Box bounds
    (reference: `NormalizeActionsConnector` inverse / `unsquash_action`).
    For modules that emit normalized actions while the env wants raw units."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)
        self.center = (self.high + self.low) / 2.0
        self.scale = (self.high - self.low) / 2.0

    def __call__(self, data: np.ndarray) -> np.ndarray:
        return self.center + self.scale * np.clip(data, -1.0, 1.0)

    def __repr__(self):
        return "UnsquashActions"
