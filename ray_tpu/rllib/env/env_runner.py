"""EnvRunner: sampling actor over vectorized gymnasium envs.

Reference: `rllib/evaluation/rollout_worker.py:166` (`sample():879`) and the
new-stack `rllib/env/env_runner.py`. Collects fixed-size rollout fragments
with the current policy weights (synced before each round), returning flat
numpy batches ready for GAE + learner sharding. Policy forward runs jitted on
the runner's CPU — sampling never touches the learner's devices.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule


class EnvRunner:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        module: RLModule,
        num_envs: int = 4,
        rollout_length: int = 128,
        seed: int = 0,
        gamma: float = 0.99,
        record_final_obs: bool = True,
        record_value_extras: bool = True,
        obs_connector: Any = None,
        action_connector: Any = None,
        exploration: Any = None,
        default_explore: bool = True,
        callbacks: Any = None,
    ):
        import gymnasium as gym
        import jax

        from ray_tpu.rllib.callbacks import DefaultCallbacks, Episode
        from ray_tpu.rllib.connectors.connector import build_connector
        from ray_tpu.rllib.utils.exploration import build_exploration

        # Worker-side lifecycle hooks (reference: callbacks run in rollout
        # workers); instantiated HERE so hook state is per-runner.
        self._callbacks = (callbacks or DefaultCallbacks)()
        self._episode_cls = Episode

        # gymnasium >=1.0 defaults vector envs to NEXT_STEP autoreset, where
        # the step after done ignores the action and returns the reset obs —
        # recording that row would corrupt the train batch. Pin the classic
        # SAME_STEP mode (reset obs returned in the done step itself, final
        # obs in infos); pre-1.0 gymnasium already behaves that way.
        if hasattr(gym.vector, "AutoresetMode"):
            self._envs = gym.vector.SyncVectorEnv(
                [env_creator for _ in range(num_envs)],
                autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
            )
        elif int(gym.__version__.split(".")[0]) >= 1:
            # gymnasium 1.0.x switched the default to NEXT_STEP but only grew
            # AutoresetMode in 1.1 — building without the kwarg there would
            # silently corrupt rollouts, so refuse instead.
            raise RuntimeError(
                f"gymnasium {gym.__version__} lacks AutoresetMode.SAME_STEP "
                "but defaults vector envs to NEXT_STEP autoreset, which "
                "corrupts rollout batches; install gymnasium>=1.1 or <1.0"
            )
        else:
            self._envs = gym.vector.SyncVectorEnv(
                [env_creator for _ in range(num_envs)]
            )
        self.module = module
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.gamma = gamma
        # `config.explore=False` (reference `AlgorithmConfig.explore`) pins
        # training rollouts deterministic; evaluate() still overrides per
        # call via sample(explore=...).
        self._default_explore = bool(default_explore)
        # Algorithms that bootstrap truncations via runner-side values (PPO)
        # skip the obs-sized final_obs buffer entirely.
        self.record_final_obs = record_final_obs
        # Algorithms whose loss recomputes values under current params
        # (IMPALA/V-trace) skip value/dist buffers and bootstrap forwards.
        self.record_value_extras = record_value_extras
        # Connector seams (reference: `rllib/connectors/`): obs transforms
        # run before the jitted forward, action transforms before env.step.
        # Built HERE (each runner actor owns fresh connector state; specs
        # pickle, stateful instances would alias across runners otherwise).
        self._obs_conn = build_connector(obs_connector)
        self._act_conn = build_connector(action_connector)
        self._key = jax.random.PRNGKey(seed)
        self._params = module.init(jax.random.PRNGKey(seed))
        self._obs, _ = self._envs.reset(seed=seed)
        # Each raw obs batch is preprocessed EXACTLY once (stateful
        # connectors like NormalizeObs accumulate per call — re-preprocessing
        # a fragment-boundary batch would double-count its moments).
        self._obs_in = self._preprocess(self._obs)
        self._episode_returns = np.zeros(num_envs)
        self._episode_lengths = np.zeros(num_envs, dtype=np.int64)
        self._completed: list = []
        # Box action spaces (continuous control) sample float vectors; the
        # rollout buffers size/type themselves off the space.
        space = self._envs.single_action_space
        self._continuous = isinstance(space, gym.spaces.Box)
        self._act_shape = space.shape if self._continuous else ()
        self._act_dtype = np.float32 if self._continuous else np.int64
        # Replay-trained modules (Q-nets, SAC) never consume logp/value/dist
        # buffers: skip filling and shipping them (and bootstrap forwards).
        self._value_based = getattr(module, "off_policy", False) or hasattr(
            module, "epsilon_greedy"
        )
        # Pluggable exploration (reference: `rllib/utils/exploration/` via
        # exploration_config). The strategy's knobs+noise ride a traced state
        # pytree through ONE jitted fn — schedule pushes and OU evolution
        # never recompile. `_clean_params` backs deterministic (explore=False)
        # action paths when ParameterNoise perturbs the rollout params.
        self._exploration = build_exploration(exploration)
        self._clean_params = self._params
        if self._exploration is not None:
            strat = self._exploration
            self._expl_state = strat.initial_state(num_envs, self._act_shape)
            jitted_s = jax.jit(
                lambda p, o, k, explore, st: strat.actions(
                    module, p, o, k, explore, st
                ),
                static_argnums=(3,),
            )

            def _strategy_act(p, o, k, explore):
                a, logp, v, d, st = jitted_s(
                    p if explore else self._clean_params, o, k, explore,
                    self._expl_state,
                )
                self._expl_state = st
                return a, logp, v, d

            self._act = _strategy_act
        elif hasattr(module, "epsilon_greedy"):
            # Value-based modules (DQN): epsilon rides as a traced scalar so
            # exploration decay never retriggers compilation.
            jitted = jax.jit(
                lambda p, o, k, explore, eps: module.epsilon_greedy(p, o, k, explore, eps),
                static_argnums=(3,),
            )
            self._epsilon = 1.0
            self._act = lambda p, o, k, explore: jitted(
                p, o, k, explore, np.float32(self._epsilon)
            )
        else:
            self._act = jax.jit(
                lambda p, o, k, explore: module.action_dist(p, o, k, explore)
            , static_argnums=(3,))

    def set_weights(self, weights) -> None:
        self._clean_params = weights
        if self._exploration is not None:
            import jax

            # ParameterNoise redraws its perturbation here (once per sync);
            # other strategies return the weights untouched.
            self._key, sub = jax.random.split(self._key)
            self._params = self._exploration.on_weights(weights, sub)
        else:
            self._params = weights

    def set_exploration(self, value) -> None:
        """Exploration push from the driver: a float (legacy DQN epsilon) or
        a dict of schedule values merged into the strategy's traced state."""
        if isinstance(value, dict):
            if self._exploration is not None:
                self._expl_state = {**self._expl_state, **value}
            return
        self._epsilon = float(value)
        if self._exploration is not None and "epsilon" in self._expl_state:
            self._expl_state = dict(self._expl_state, epsilon=np.float32(value))

    # ------------------------------------------------------------- connectors
    def _preprocess(self, obs) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        return self._obs_conn(obs) if self._obs_conn is not None else obs

    def get_connector_state(self):
        return self._obs_conn.state() if self._obs_conn is not None else {}

    def set_connector_state(self, state, freeze: bool = False) -> None:
        """Adopt another runner's connector state (evaluation runners run on
        the training runners' normalization stats, frozen so eval batches
        don't pollute them — reference: `MeanStdFilter` sync semantics)."""
        if self._obs_conn is None:
            return
        self._obs_conn.set_state(state)
        if freeze and hasattr(self._obs_conn, "frozen"):
            self._obs_conn.frozen = True
        for c in getattr(self._obs_conn, "connectors", []):
            if freeze and hasattr(c, "frozen"):
                c.frozen = True

    def sample(self, explore: Optional[bool] = None) -> Dict[str, np.ndarray]:
        """One rollout fragment: (T*num_envs) flat transition batch."""
        import jax

        if explore is None:
            explore = self._default_explore
        T, N = self.rollout_length, self.num_envs
        value_based = self._value_based
        need_logp = not value_based
        need_values = not value_based and self.record_value_extras
        # The train batch records the CONNECTED obs — the loss must see
        # exactly what the policy forward saw. Carried from the previous
        # fragment (preprocessed once there).
        obs_in = self._obs_in
        obs_buf = np.zeros((T, N) + obs_in.shape[1:], np.float32)
        act_buf = np.zeros((T, N) + self._act_shape, self._act_dtype)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        term_buf = np.zeros((T, N), np.float32)
        if need_logp:
            logp_buf = np.zeros((T, N), np.float32)
        if need_values:
            val_buf = np.zeros((T, N), np.float32)
            # V(final_obs) where an episode hit its time limit: GAE bootstraps
            # truncated episodes through this value (reference:
            # compute_advantages bootstraps with vf(last_obs) at time limits).
            boot_buf = np.zeros((T, N), np.float32)
        # True final observation at truncation boundaries (SAME_STEP autoreset
        # replaces next_obs with the reset obs there); value-based algorithms
        # bootstrap their TD targets through these rows.
        final_obs_buf = (
            np.zeros((T, N) + obs_in.shape[1:], np.float32)
            if self.record_final_obs
            else None
        )
        trunc_buf = np.zeros((T, N), np.float32)
        logits_buf: Optional[np.ndarray] = None
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, value, logits = self._act(
                self._params, obs_in, sub, explore
            )
            action = np.asarray(action)
            if need_logp:
                logp_buf[t] = np.asarray(logp)
            if need_values:
                if logits_buf is None:
                    logits_buf = np.zeros((T, N) + np.shape(logits)[1:], np.float32)
                logits_buf[t] = np.asarray(logits)
                val_buf[t] = np.asarray(value)
            obs_buf[t] = obs_in
            act_buf[t] = action
            env_action = (
                self._act_conn(action) if self._act_conn is not None else action
            )
            nxt, rew, term, trunc, infos = self._envs.step(env_action)
            done = np.logical_or(term, trunc)
            rew_buf[t] = rew
            done_buf[t] = done.astype(np.float32)
            term_buf[t] = np.asarray(term, np.float32)
            trunc_only = np.logical_and(trunc, np.logical_not(term))
            if trunc_only.any():
                idx = np.nonzero(trunc_only)[0]
                raw_final = self._final_observations(infos, nxt)
                # Connect ONLY the truly-final rows (the rest are next-step
                # obs that will be preprocessed at loop end — connecting
                # them here would double-count their normalization moments),
                # then scatter into a full batch so the jitted forward keeps
                # one shape. Non-idx rows are zero and never read.
                pf_rows = self._preprocess(raw_final[idx])
                final_obs = np.zeros_like(obs_in)
                final_obs[idx] = pf_rows
                trunc_buf[t, idx] = 1.0
                if final_obs_buf is not None:
                    final_obs_buf[t, idx] = pf_rows
                if need_values:
                    self._key, sub = jax.random.split(self._key)
                    _, _, fvals, _ = self._act(
                        self._params, final_obs, sub, False
                    )
                    boot_buf[t, idx] = np.asarray(fvals, np.float32)[idx]
            self._episode_returns += rew
            self._episode_lengths += 1
            for i in np.nonzero(done)[0]:
                ep = (float(self._episode_returns[i]), int(self._episode_lengths[i]))
                self._completed.append(ep)
                self._callbacks.on_episode_end(
                    episode=self._episode_cls(
                        episode_return=ep[0], episode_length=ep[1]
                    )
                )
                self._episode_returns[i] = 0.0
                self._episode_lengths[i] = 0
            self._obs = nxt
            self._obs_in = obs_in = self._preprocess(self._obs)
        out = {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "terminateds": term_buf,
            "truncateds": trunc_buf,
            # Final observations (value-based algorithms build next_obs by
            # shifting obs and closing the tail with these).
            "last_obs": obs_in,
        }
        if final_obs_buf is not None:
            out["final_obs"] = final_obs_buf
        if need_logp:
            out["logp"] = logp_buf
        if need_values:
            # Bootstrap value for the final observation of each env.
            self._key, sub = jax.random.split(self._key)
            _, _, last_val, _ = self._act(self._params, obs_in, sub, explore)
            out.update(
                behavior_logits=logits_buf,
                values=val_buf,
                bootstrap_values=boot_buf,
                last_values=np.asarray(last_val, np.float32),
            )
        self._callbacks.on_sample_end(samples=out)
        return out

    def _final_observations(self, infos, nxt: np.ndarray) -> np.ndarray:
        """Per-env final observations for done envs (SAME_STEP autoreset puts
        them in infos; fall back to the post-step obs when absent)."""
        finals = None
        for key in ("final_obs", "final_observation"):
            if key in infos:
                finals = infos[key]
                break
        out = np.array(nxt, copy=True)
        if finals is not None:
            for i, f in enumerate(finals):
                if f is not None:
                    out[i] = f
        return out

    def episode_stats(self, clear: bool = True) -> Dict[str, float]:
        eps = self._completed
        if clear:
            self._completed = []
        if not eps:
            return {"episodes": 0}
        rets = [r for r, _ in eps]
        lens = [l for _, l in eps]
        return {
            "episodes": len(eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }
