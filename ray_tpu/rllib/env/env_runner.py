"""EnvRunner: sampling actor over vectorized gymnasium envs.

Reference: `rllib/evaluation/rollout_worker.py:166` (`sample():879`) and the
new-stack `rllib/env/env_runner.py`. Collects fixed-size rollout fragments
with the current policy weights (synced before each round), returning flat
numpy batches ready for GAE + learner sharding. Policy forward runs jitted on
the runner's CPU — sampling never touches the learner's devices.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule


class EnvRunner:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        module: RLModule,
        num_envs: int = 4,
        rollout_length: int = 128,
        seed: int = 0,
        gamma: float = 0.99,
    ):
        import gymnasium as gym
        import jax

        self._envs = gym.vector.SyncVectorEnv(
            [env_creator for _ in range(num_envs)]
        )
        self.module = module
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.gamma = gamma
        self._key = jax.random.PRNGKey(seed)
        self._params = module.init(jax.random.PRNGKey(seed))
        self._obs, _ = self._envs.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs)
        self._episode_lengths = np.zeros(num_envs, dtype=np.int64)
        self._completed: list = []
        self._act = jax.jit(
            lambda p, o, k, explore: module.action_dist(p, o, k, explore)
        , static_argnums=(3,))

    def set_weights(self, weights) -> None:
        self._params = weights

    def sample(self, explore: bool = True) -> Dict[str, np.ndarray]:
        """One rollout fragment: (T*num_envs) flat transition batch."""
        import jax

        T, N = self.rollout_length, self.num_envs
        obs_buf = np.zeros((T, N) + self._obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N), np.int64)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        logits_buf: Optional[np.ndarray] = None
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, value, logits = self._act(
                self._params, self._obs.astype(np.float32), sub, explore
            )
            action = np.asarray(action)
            if logits_buf is None:
                logits_buf = np.zeros((T, N) + np.shape(logits)[1:], np.float32)
            logits_buf[t] = np.asarray(logits)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            nxt, rew, term, trunc, _ = self._envs.step(action)
            done = np.logical_or(term, trunc)
            rew_buf[t] = rew
            done_buf[t] = done.astype(np.float32)
            self._episode_returns += rew
            self._episode_lengths += 1
            for i in np.nonzero(done)[0]:
                self._completed.append(
                    (float(self._episode_returns[i]), int(self._episode_lengths[i]))
                )
                self._episode_returns[i] = 0.0
                self._episode_lengths[i] = 0
            self._obs = nxt
        # Bootstrap value for the final observation of each env.
        self._key, sub = jax.random.split(self._key)
        _, _, last_val, _ = self._act(
            self._params, self._obs.astype(np.float32), sub, explore
        )
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "behavior_logits": logits_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "last_values": np.asarray(last_val, np.float32),
        }

    def episode_stats(self, clear: bool = True) -> Dict[str, float]:
        eps = self._completed
        if clear:
            self._completed = []
        if not eps:
            return {"episodes": 0}
        rets = [r for r, _ in eps]
        lens = [l for _, l in eps]
        return {
            "episodes": len(eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }
