"""MultiAgentEnvRunner: sampling actor over MultiAgentEnv instances with
per-policy action routing.

Reference: `rllib/evaluation/rollout_worker.py` multi-agent path — obs are
routed to policies via `policy_mapping_fn(agent_id)`, actions route back, and
each policy accumulates its own train batch
(`rllib/evaluation/episode.py` + `sample_batch_builder`). The TPU-first
difference: per step, all agents mapped to the same policy batch into ONE
jitted forward (the reference loops per-agent through eager torch), and GAE
runs here on the completed per-agent trajectories so the learner receives
flat, shard-ready per-policy batches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.callbacks import Episode as _Episode


def _segment_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    bootstrap: float,
    gamma: float,
    lambda_: float,
) -> Tuple[np.ndarray, np.ndarray]:
    """GAE over one contiguous single-agent trajectory segment. `bootstrap`
    is V(next_obs) after the last row (0.0 when the segment terminated)."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    lastgaelam = 0.0
    for t in reversed(range(T)):
        next_v = bootstrap if t == T - 1 else values[t + 1]
        delta = rewards[t] + gamma * next_v - values[t]
        lastgaelam = delta + gamma * lambda_ * lastgaelam
        adv[t] = lastgaelam
    return adv, adv + values


class _Trajectory:
    """Per-(env, agent) rollout accumulator."""

    __slots__ = ("obs", "actions", "logp", "logits", "values", "rewards")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[Any] = []
        self.logp: List[float] = []
        self.logits: List[np.ndarray] = []
        self.values: List[float] = []
        self.rewards: List[float] = []

    def __len__(self):
        return len(self.actions)


class MultiAgentEnvRunner:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        modules: Dict[str, Any],  # policy_id -> RLModule
        policy_mapping_fn: Callable[[str], str],
        num_envs: int = 2,
        rollout_length: int = 128,
        seed: int = 0,
        gamma: float = 0.99,
        lambda_: float = 0.95,
        default_explore: bool = True,
        callbacks=None,
    ):
        import jax

        from ray_tpu.rllib.callbacks import DefaultCallbacks

        # Worker-side lifecycle hooks (parity with EnvRunner).
        self._callbacks = (callbacks or DefaultCallbacks)()

        self._envs = [env_creator() for _ in range(num_envs)]
        # `config.explore=False` pins training rollouts deterministic.
        self._default_explore = bool(default_explore)
        self.modules = modules
        self.policy_mapping_fn = policy_mapping_fn
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.lambda_ = lambda_
        self._key = jax.random.PRNGKey(seed)
        self._params = {
            pid: m.init(jax.random.PRNGKey(seed + i))
            for i, (pid, m) in enumerate(modules.items())
        }
        # Replay-trained policy maps (multi-agent DQN/SAC): trajectories
        # close into flat (s, a, r, s', terminated) transition batches per
        # policy instead of GAE columns, and Q modules act epsilon-greedily
        # with a driver-pushed schedule (same contract as EnvRunner).
        self.value_based = any(
            getattr(m, "off_policy", False) or hasattr(m, "epsilon_greedy")
            for m in modules.values()
        )
        self._epsilon = 1.0
        self._act = {}
        for pid, m in modules.items():
            if hasattr(m, "epsilon_greedy"):
                jitted = jax.jit(
                    (lambda mod: lambda p, o, k, explore, eps: mod.epsilon_greedy(
                        p, o, k, explore, eps
                    ))(m),
                    static_argnums=(3,),
                )
                self._act[pid] = (
                    lambda p, o, k, explore, _j=jitted: _j(
                        p, o, k, explore, np.float32(self._epsilon)
                    )
                )
            else:
                self._act[pid] = jax.jit(
                    (lambda mod: lambda p, o, k, explore: mod.action_dist(
                        p, o, k, explore
                    ))(m),
                    static_argnums=(3,),
                )
        # Live episode state per env.
        self._obs: List[Dict[str, Any]] = []
        self._done_agents: List[set] = []
        self._episode_return: List[float] = []
        self._episode_len: List[int] = []
        self._completed: List[Tuple[float, int]] = []
        for i, env in enumerate(self._envs):
            obs, _ = env.reset(seed=seed + 7919 * (i + 1))
            self._obs.append(obs)
            self._done_agents.append(set())
            self._episode_return.append(0.0)
            self._episode_len.append(0)
        # Open per-(env, agent-id) trajectories.
        self._traj: List[Dict[str, _Trajectory]] = [dict() for _ in self._envs]

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            self._params[pid] = w

    def set_exploration(self, epsilon: float) -> None:
        """Epsilon push for Q policies (schedule lives in the driver)."""
        self._epsilon = float(epsilon)

    # ------------------------------------------------------------------ sample
    def sample(self, explore=None) -> Dict[str, Dict[str, np.ndarray]]:
        """Collect `rollout_length` env steps; returns per-policy flat batches:
        GAE columns (advantages/value_targets) for policy-gradient maps, or
        (s, a, r, s', terminated) transitions for replay-trained maps."""
        if explore is None:
            explore = self._default_explore
        if self.value_based:
            keys = (
                "obs", "actions", "rewards", "next_obs",
                "terminateds", "loss_weight",
            )
        else:
            keys = (
                "obs", "actions", "logp", "behavior_logits",
                "advantages", "value_targets",
            )
        out: Dict[str, Dict[str, List[np.ndarray]]] = {
            pid: {k: [] for k in keys} for pid in self.modules
        }
        for _ in range(self.rollout_length):
            self._step_once(out, explore)
        # Close out still-open trajectories (episode continues next fragment):
        # PG bootstraps through V(current obs); replay transitions tail with
        # s' = current obs, terminated=0 (the target net bootstraps).
        for e in range(len(self._envs)):
            open_agents = list(self._traj[e].keys())
            if not open_agents:
                continue
            if self.value_based:
                for aid in open_agents:
                    self._close_trajectory(
                        out, e, aid,
                        close_obs=self._obs[e].get(aid), terminated=False,
                    )
            else:
                boots = self._values_for(
                    {aid: self._obs[e][aid] for aid in open_agents if aid in self._obs[e]}
                )
                for aid in open_agents:
                    self._close_trajectory(out, e, aid, boots.get(aid, 0.0))
        batches = {
            pid: {k: _stack(v) for k, v in cols.items()}
            for pid, cols in out.items()
            if cols["actions"]
        }
        self._callbacks.on_sample_end(samples=batches)
        return batches

    def _group_by_policy(
        self, per_env_obs: List[Dict[str, Any]]
    ) -> Dict[str, List[Tuple[int, str]]]:
        """(env_idx, agent_id) pairs ready to act, grouped by policy."""
        groups: Dict[str, List[Tuple[int, str]]] = {}
        for e, obs in enumerate(per_env_obs):
            for aid in obs:
                if aid in self._done_agents[e]:
                    continue
                groups.setdefault(self.policy_mapping_fn(aid), []).append((e, aid))
        return groups

    def _step_once(self, out, explore: bool) -> None:
        import jax

        groups = self._group_by_policy(self._obs)
        actions: List[Dict[str, Any]] = [dict() for _ in self._envs]
        for pid, members in groups.items():
            obs_batch = np.stack(
                [np.asarray(self._obs[e][aid], np.float32).ravel() for e, aid in members]
            )
            self._key, sub = jax.random.split(self._key)
            a, logp, value, logits = self._act[pid](
                self._params[pid], obs_batch, sub, explore
            )
            a = np.asarray(a)
            logp = np.asarray(logp)
            value = np.asarray(value)
            logits = np.asarray(logits)
            for j, (e, aid) in enumerate(members):
                tr = self._traj[e].setdefault(aid, _Trajectory())
                tr.obs.append(obs_batch[j])
                tr.actions.append(a[j])
                if not self.value_based:
                    tr.logp.append(float(logp[j]))
                    tr.logits.append(logits[j])
                    tr.values.append(float(value[j]))
                actions[e][aid] = a[j]
        for e, env in enumerate(self._envs):
            if not actions[e]:
                self._reset_env(e)
                continue
            obs, rews, terms, truncs, infos = env.step(actions[e])
            for aid, r in rews.items():
                # An action opens a pending reward slot (len(rewards) ==
                # len(actions) - 1). Rewards reported on steps where the agent
                # did NOT act (turn-based envs: agent absent from obs is "not
                # ready") accumulate into the last acted step instead of
                # appending — appending would desynchronize rewards[i] from
                # actions[i] and misattribute credit in GAE.
                tr = self._traj[e].get(aid)
                if tr is not None and len(tr.actions):
                    if len(tr.rewards) < len(tr.actions):
                        tr.rewards.append(float(r))
                    else:
                        tr.rewards[-1] += float(r)
                self._episode_return[e] += float(r)
            self._episode_len[e] += 1
            next_obs = dict(self._obs[e])
            next_obs.update(obs)
            for aid in list(rews):
                terminated = bool(terms.get(aid, False))
                truncated = bool(truncs.get(aid, False))
                if terminated or truncated:
                    self._done_agents[e].add(aid)
                    if self.value_based:
                        self._close_trajectory(
                            out, e, aid,
                            close_obs=obs.get(aid), terminated=terminated,
                        )
                    else:
                        boot = 0.0
                        if truncated and not terminated and aid in obs:
                            boot = self._values_for({aid: obs[aid]}).get(aid, 0.0)
                        self._close_trajectory(out, e, aid, boot)
            self._obs[e] = next_obs
            if terms.get("__all__") or truncs.get("__all__"):
                # Close any trajectories still open (an env may end the whole
                # episode via __all__ without per-agent terminal flags):
                # truncation-style end bootstraps through V(last obs),
                # termination cuts to zero — and either way the buffers must
                # not leak into the next episode.
                open_agents = list(self._traj[e].keys())
                if open_agents:
                    if self.value_based:
                        terminated_all = bool(terms.get("__all__"))
                        for aid in open_agents:
                            self._close_trajectory(
                                out, e, aid,
                                close_obs=next_obs.get(aid),
                                terminated=terminated_all,
                            )
                    else:
                        boots = (
                            self._values_for(
                                {
                                    aid: next_obs[aid]
                                    for aid in open_agents
                                    if aid in next_obs
                                }
                            )
                            if truncs.get("__all__")
                            else {}
                        )
                        for aid in open_agents:
                            self._close_trajectory(out, e, aid, boots.get(aid, 0.0))
                self._completed.append(
                    (self._episode_return[e], self._episode_len[e])
                )
                self._callbacks.on_episode_end(
                    episode=_Episode(
                        episode_return=float(self._episode_return[e]),
                        episode_length=int(self._episode_len[e]),
                    )
                )
                self._reset_env(e)

    def _reset_env(self, e: int) -> None:
        obs, _ = self._envs[e].reset()
        self._obs[e] = obs
        self._done_agents[e] = set()
        self._episode_return[e] = 0.0
        self._episode_len[e] = 0

    def _values_for(self, obs_by_agent: Dict[str, Any]) -> Dict[str, float]:
        """V(obs) per agent under the agent's policy (bootstrap helper)."""
        import jax

        vals: Dict[str, float] = {}
        groups: Dict[str, List[str]] = {}
        for aid in obs_by_agent:
            groups.setdefault(self.policy_mapping_fn(aid), []).append(aid)
        for pid, aids in groups.items():
            batch = np.stack(
                [np.asarray(obs_by_agent[a], np.float32).ravel() for a in aids]
            )
            self._key, sub = jax.random.split(self._key)
            _, _, value, _ = self._act[pid](self._params[pid], batch, sub, False)
            for a, v in zip(aids, np.asarray(value)):
                vals[a] = float(v)
        return vals

    def _close_trajectory(
        self, out, e: int, aid: str, bootstrap: float = 0.0,
        close_obs: Any = None, terminated: bool = False,
    ) -> None:
        tr = self._traj[e].pop(aid, None)
        if tr is None or len(tr) == 0:
            return
        # A trailing action whose reward was never reported (episode ended via
        # __all__ before the env credited it) earns 0. Rewards can never
        # exceed actions: inter-action rewards fold into the last acted step.
        if len(tr.rewards) < len(tr.actions):
            tr.rewards.extend([0.0] * (len(tr.actions) - len(tr.rewards)))
        assert len(tr.rewards) == len(tr.actions), (
            f"trajectory desync for {aid}: "
            f"{len(tr.rewards)} rewards vs {len(tr.actions)} actions"
        )
        n = len(tr.actions)
        rewards = np.asarray(tr.rewards, np.float32)
        pid = self.policy_mapping_fn(aid)
        cols = out[pid]
        if self.value_based:
            # Flat replay transitions: s'[i] is the agent's NEXT observation
            # (consecutive within the trajectory; skipped turn-based steps
            # collapse into one transition). The tail's s' is `close_obs`
            # (the final/current obs); terminated marks only the tail row —
            # a fragment-end close bootstraps through the target net.
            obs_arr = np.stack(tr.obs)
            weight = np.ones(n, np.float32)
            if close_obs is not None:
                last_next = np.asarray(close_obs, np.float32).ravel()
            else:
                # No final obs for the tail. Terminated rows never read s'
                # (the TD target zeroes it); a TRUNCATED/fragment close
                # without an obs would bootstrap through its own source
                # state — exclude that row instead (same rule as the
                # single-agent fallback in DQN._transitions).
                last_next = obs_arr[-1]
                if not terminated:
                    weight[-1] = 0.0
            next_obs = np.concatenate([obs_arr[1:], last_next[None]], axis=0)
            term_col = np.zeros(n, np.float32)
            term_col[-1] = 1.0 if terminated else 0.0
            cols["obs"].append(obs_arr)
            cols["actions"].append(np.asarray(tr.actions))
            cols["rewards"].append(rewards)
            cols["next_obs"].append(next_obs)
            cols["terminateds"].append(term_col)
            cols["loss_weight"].append(weight)
            return
        values = np.asarray(tr.values, np.float32)
        adv, targets = _segment_gae(
            rewards, values, bootstrap, self.gamma, self.lambda_
        )
        cols["obs"].append(np.stack(tr.obs[:n]))
        cols["actions"].append(np.asarray(tr.actions[:n]))
        cols["logp"].append(np.asarray(tr.logp[:n], np.float32))
        cols["behavior_logits"].append(np.stack(tr.logits[:n]))
        cols["advantages"].append(adv)
        cols["value_targets"].append(targets)

    # ------------------------------------------------------------------- stats
    def episode_stats(self, clear: bool = True) -> Dict[str, float]:
        eps = self._completed
        if clear:
            self._completed = []
        if not eps:
            return {"episodes": 0}
        rets = [r for r, _ in eps]
        lens = [l for _, l in eps]
        return {
            "episodes": len(eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }


def _stack(chunks: List[np.ndarray]) -> np.ndarray:
    return np.concatenate(chunks, axis=0)
