"""MultiAgentEnv: one environment hosting many independently-acting agents.

Reference: `rllib/env/multi_agent_env.py:30` — agents are string ids; reset
and step speak per-agent dicts; the terminated/truncated dicts carry the
special `"__all__"` key marking whole-episode end. `make_multi_agent`
(reference `multi_agent_env.py:284`) turns any single-agent gymnasium env
into a MultiAgentEnv of N independent copies — the standard test substrate.

The runner contract (see `MultiAgentEnvRunner`):
- `reset()` returns (obs_dict, info_dict) for every agent ready to act.
- `step(action_dict)` takes actions ONLY for agents that appeared in the
  previous obs dict, and returns per-agent obs/reward/terminated/truncated/
  info dicts. Agents absent from the returned obs dict are done (or simply
  not ready); `terminateds["__all__"]`/`truncateds["__all__"]` end the
  episode for everyone.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Set, Tuple, Union

MultiAgentDict = Dict[str, Any]


class MultiAgentEnv:
    """Base class. Subclasses implement reset/step over per-agent dicts and
    (preferably) expose `observation_space`/`action_space` as dicts mapping
    agent id -> gymnasium space."""

    # Dict agent_id -> space when in the preferred format.
    observation_space: Any = None
    action_space: Any = None

    def get_agent_ids(self) -> Set[str]:
        if isinstance(self.observation_space, dict):
            return set(self.observation_space)
        return set()

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[dict] = None
    ) -> Tuple[MultiAgentDict, MultiAgentDict]:
        raise NotImplementedError

    def step(
        self, action_dict: MultiAgentDict
    ) -> Tuple[
        MultiAgentDict, MultiAgentDict, MultiAgentDict, MultiAgentDict, MultiAgentDict
    ]:
        raise NotImplementedError

    def close(self) -> None:
        pass


def make_multi_agent(
    env_name_or_creator: Union[str, Callable[[], Any]],
) -> Callable[[Optional[dict]], MultiAgentEnv]:
    """Wrap a single-agent env as N independent agents (one sub-env each).

    Reference semantics (`multi_agent_env.py:284` `make_multi_agent`): agent
    ids are 0..N-1 (stringified here), each steps its own copy; a done
    sub-env's agent drops out of subsequent obs dicts; `"__all__"` turns True
    once every sub-env is done.
    """

    def creator(config: Optional[dict] = None) -> MultiAgentEnv:
        config = config or {}
        num = int(config.get("num_agents", 1))

        def make_one():
            if callable(env_name_or_creator):
                return env_name_or_creator()
            import gymnasium as gym

            kwargs = {
                k: v for k, v in config.items() if k != "num_agents"
            }
            return gym.make(env_name_or_creator, **kwargs)

        class _IndependentMultiEnv(MultiAgentEnv):
            def __init__(self):
                self._envs = {str(i): make_one() for i in range(num)}
                self.observation_space = {
                    aid: e.observation_space for aid, e in self._envs.items()
                }
                self.action_space = {
                    aid: e.action_space for aid, e in self._envs.items()
                }
                self._done: Set[str] = set()
                self._terminated: Set[str] = set()

            def reset(self, *, seed=None, options=None):
                self._done = set()
                self._terminated = set()
                obs, infos = {}, {}
                for i, (aid, env) in enumerate(self._envs.items()):
                    s = None if seed is None else seed + i
                    obs[aid], infos[aid] = env.reset(seed=s, options=options)
                return obs, infos

            def step(self, action_dict):
                obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
                for aid, action in action_dict.items():
                    if aid in self._done:
                        continue
                    o, r, te, tr, info = self._envs[aid].step(action)
                    rews[aid] = r
                    terms[aid] = bool(te)
                    truncs[aid] = bool(tr)
                    infos[aid] = info
                    obs[aid] = o  # final obs still reported for bootstrap
                    if te or tr:
                        self._done.add(aid)
                        if te:
                            self._terminated.add(aid)
                all_done = len(self._done) == len(self._envs)
                terms["__all__"] = all_done and self._done == self._terminated
                truncs["__all__"] = all_done and not terms["__all__"]
                return obs, rews, terms, truncs, infos

            def close(self):
                for env in self._envs.values():
                    env.close()

        return _IndependentMultiEnv()

    return creator
