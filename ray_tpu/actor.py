"""Actors: stateful workers with ordered method dispatch.

Reference: `python/ray/actor.py` (`ActorClass:377`, `ActorClass._remote:659`,
`ActorHandle._actor_method_call:1111`); creation is registered with the GCS actor
manager which leases a dedicated worker (`gcs_actor_manager.h:281`), and method
calls go directly to that worker, ordered by the submission sequence
(`transport/actor_scheduling_queue.h`). Here the dedicated worker is a spawned
process whose main loop executes its queue in order.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private import serialization, worker as worker_mod
from ray_tpu._private.gcs import ActorInfo
from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.protocol import ExecRequest, FunctionDescriptor, TaskSpec
from ray_tpu._private.scheduler import ActorRecord
from ray_tpu._private.worker import ObjectRef, global_worker
from ray_tpu.remote_function import _apply_strategy, _resources_from_options

_VALID_ACTOR_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "num_gpus",
    "resources",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "concurrency_groups",
    "name",
    "namespace",
    "lifetime",
    "scheduling_strategy",
    "runtime_env",
    "memory",
    "get_if_exists",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 generator_backpressure: Optional[int] = None,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._generator_backpressure = generator_backpressure
        self._concurrency_group = concurrency_group

    def options(self, **opts) -> "ActorMethod":
        # Unspecified options keep their declared (decorator) values — an
        # .options(concurrency_group=...) call must not silently reset a
        # @method(num_returns=2) declaration back to 1.
        return ActorMethod(
            self._handle,
            self._name,
            opts.get("num_returns", self._num_returns),
            opts.get("generator_backpressure", self._generator_backpressure),
            opts.get("concurrency_group", self._concurrency_group),
        )

    def remote(self, *args, **kwargs):
        return self._handle._actor_method_call(
            self._name, args, kwargs, self._num_returns,
            self._generator_backpressure,
            concurrency_group=self._concurrency_group,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._name}' cannot be called directly; use "
            f"'.{self._name}.remote()'."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "Actor",
                 method_meta: Optional[Dict[str, int]] = None,
                 method_groups: Optional[Dict[str, str]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        # method name -> num_returns, collected from @ray_tpu.method decorators.
        self._method_meta = method_meta or {}
        # method name -> declared concurrency group (@ray_tpu.method(
        # concurrency_group=...)); .options() on the call site overrides.
        self._method_groups = method_groups or {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(
            self, name, self._method_meta.get(name, 1),
            concurrency_group=self._method_groups.get(name),
        )

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._class_name, self._method_meta,
             self._method_groups),
        )

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def _actor_method_call(self, method_name: str, args, kwargs, num_returns,
                           generator_backpressure: Optional[int] = None,
                           concurrency_group: Optional[str] = None):
        from ray_tpu.remote_function import _resolve_backpressure

        returns_mode = None
        backpressure = _resolve_backpressure(
            {"generator_backpressure": generator_backpressure}, num_returns
        )
        if num_returns in ("dynamic", "streaming"):
            # Generator actor method (sync generators, or `async def` methods
            # yielding via an async generator — the basis of Serve streaming
            # responses; reference: `_raylet.pyx` streaming generator actor
            # tasks).
            returns_mode = num_returns
            num_returns = 1 if returns_mode == "dynamic" else 0
        task_id = global_worker.next_task_id()
        spec = TaskSpec(
            task_id=task_id,
            func=FunctionDescriptor("", method_name),
            num_returns=num_returns,
            returns_mode=returns_mode,
            generator_backpressure=backpressure,
            actor_id=self._actor_id,
            method_name=method_name,
            name=f"{self._class_name}.{method_name}",
            concurrency_group=concurrency_group,
        )
        from ray_tpu.util import tracing

        submit_span = None
        if tracing.is_enabled():
            # None = unsampled root: no context rides the spec.
            submit_span = tracing.start_span(
                f"actor::{spec.name}", "submit", attributes={"task_id": task_id.hex()}
            )
            if submit_span is not None:
                spec.trace_context = tracing.context_of(submit_span)
                spec.env_vars.setdefault("RAY_TPU_TRACING", "1")
        try:
            entries, kwentries = worker_mod._serialize_arg_entries(args, kwargs)
            return_ids = [ObjectID.for_return(task_id, i + 1) for i in range(num_returns)]
            # Owner-side record (ownership.py): registered before the submit
            # so the seal forward resolves this process's gets in-process.
            if return_ids:
                global_worker.ownership.expect(
                    [oid.binary() for oid in return_ids]
                )
            req = ExecRequest(spec=spec, arg_metas=[], kwarg_metas={}, return_ids=return_ids)
            req._arg_entries = entries
            req._kwarg_entries = kwentries
            global_worker.context.submit_actor_task(req)
        finally:
            if submit_span is not None:
                tracing.end_span(submit_span)
        if returns_mode == "streaming":
            return worker_mod.ObjectRefGenerator(task_id)
        refs = [ObjectRef(oid) for oid in return_ids]
        return refs[0] if num_returns == 1 else refs

    @property
    def __ray_ready__(self):  # parity helper: `get(actor.__ray_ready__.remote())`
        return ActorMethod(self, "__ray_ready__")


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        for k in self._options:
            if k not in _VALID_ACTOR_OPTIONS:
                raise ValueError(f"Invalid actor option: {k}")
        self._blob: Optional[bytes] = None
        self._function_id: Optional[str] = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        ac = ActorClass(self._cls, merged)
        ac._blob = self._blob
        ac._function_id = self._function_id
        return ac

    def bind(self, *args, **kwargs):
        """Build a lazy actor DAG node (reference: `dag/class_node.py`)."""
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker_mod._auto_init()
        opts = self._options
        name = opts.get("name")
        lifetime = opts.get("lifetime")
        if lifetime not in (None, "detached", "non_detached"):
            # An unknown lifetime must not silently downgrade to "owned".
            raise ValueError(
                f'lifetime must be "detached" or "non_detached", got {lifetime!r}'
            )
        if name and opts.get("get_if_exists"):
            existing = global_worker.context.get_actor_by_name(name)
            if existing is not None:
                return ActorHandle(existing, self._cls.__name__)
        if self._blob is None:
            self._blob = serialization.dumps(self._cls)
            self._function_id = worker_mod.function_id_of(self._blob)
        actor_id = ActorID.of(global_worker.job_id)
        task_id = global_worker.next_task_id()
        resources = _resources_from_options(opts, default_cpus=0.0)
        renv = dict(opts.get("runtime_env") or {})
        spec = TaskSpec(
            task_id=task_id,
            func=FunctionDescriptor(self._function_id, self._cls.__name__),
            num_returns=0,
            resources=resources,
            actor_id=actor_id,
            is_actor_creation=True,
            name=f"{self._cls.__name__}.__init__",
            max_concurrency=max(1, int(opts.get("max_concurrency", 1))),
            concurrency_groups=(
                {str(g): int(n) for g, n in opts["concurrency_groups"].items()}
                if opts.get("concurrency_groups")
                else None
            ),
            env_vars=dict(renv.get("env_vars") or {}),
            runtime_env={k: v for k, v in renv.items() if k != "env_vars"} or None,
        )
        _apply_strategy(spec, opts.get("scheduling_strategy"))
        from ray_tpu.util import tracing

        submit_span = None
        if tracing.is_enabled():
            # Creation submit span: the worker-side creation execute span
            # (worker_main._execute) parents onto it via spec.trace_context,
            # same as task and method-call submissions.
            submit_span = tracing.start_span(
                f"actor_create::{self._cls.__name__}", "submit",
                attributes={"actor_id": actor_id.hex(), "task_id": task_id.hex()},
            )
            if submit_span is not None:
                spec.trace_context = tracing.context_of(submit_span)
                spec.env_vars.setdefault("RAY_TPU_TRACING", "1")
        try:
            entries, kwentries = worker_mod._serialize_arg_entries(args, kwargs)
            req = ExecRequest(
                spec=spec, arg_metas=[], kwarg_metas={}, func_blob=self._blob, return_ids=[]
            )
            req._saved_arg_entries = entries
            req._saved_kwarg_entries = kwentries
            from ray_tpu._private.config import get_config

            max_restarts = int(
                opts.get("max_restarts", get_config().actor_max_restarts)
            )
            if max_restarts < 0:  # -1 = infinite, like the reference
                max_restarts = 1 << 30
            ar = ActorRecord(
                actor_id=actor_id,
                creation_req=req,
                resources=resources,
                max_restarts=max_restarts,
                detached=(lifetime == "detached"),
            )
            info = ActorInfo(
                actor_id=actor_id,
                name=name,
                class_name=self._cls.__name__,
                max_restarts=max_restarts,
            )
            global_worker.context.create_actor((ar, info, name))
        finally:
            if submit_span is not None:
                tracing.end_span(submit_span)
        method_meta = {
            n: getattr(m, "__ray_tpu_num_returns__")
            for n, m in vars(self._cls).items()
            if callable(m) and hasattr(m, "__ray_tpu_num_returns__")
        }
        method_groups = {
            n: getattr(m, "__ray_tpu_concurrency_group__")
            for n, m in vars(self._cls).items()
            if callable(m) and getattr(m, "__ray_tpu_concurrency_group__", None)
        }
        return ActorHandle(actor_id, self._cls.__name__, method_meta, method_groups)


def method(**opts):
    """`@ray_tpu.method(num_returns=n, concurrency_group="io")` decorator for
    actor methods (reference: `python/ray/actor.py` `@ray.method`)."""

    def decorator(fn):
        fn.__ray_tpu_num_returns__ = opts.get("num_returns", 1)
        if opts.get("concurrency_group"):
            fn.__ray_tpu_concurrency_group__ = str(opts["concurrency_group"])
        return fn

    return decorator
