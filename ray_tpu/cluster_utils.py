"""In-process multi-node cluster fixture for tests.

The reference's load-bearing test trick (`python/ray/cluster_utils.py:99
class Cluster` / `add_node:165`): N real raylets on one machine, each pretending to
be a node, so GCS + scheduler behave exactly as on a real cluster. Here nodes are
virtual NodeState entries in the driver's scheduler, each with its own resource
spec and worker pool, so spillback / SPREAD / STRICT_SPREAD / node-failure paths
are all exercised without extra machines.
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu._private.ids import NodeID
from ray_tpu._private.worker import DriverContext, global_worker, init, shutdown


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        connect: bool = True,
        head_node_args: Optional[Dict] = None,
    ):
        self._node_ids = []
        if initialize_head:
            args = dict(head_node_args or {})
            args.setdefault("num_cpus", 1)
            init(**args)
            ctx: DriverContext = global_worker.context
            self._scheduler = ctx.scheduler
            head_nodes = ctx.nodes()
            self._node_ids.append(NodeID.from_hex(head_nodes[0]["node_id"]))
        else:
            raise ValueError("Cluster without a head node is not supported")

    @property
    def head_node_id(self) -> NodeID:
        return self._node_ids[0]

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> NodeID:
        node_resources = {"CPU": float(num_cpus)}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        node_resources.update(resources or {})
        node_id = self._scheduler.call("add_node", (node_resources, labels or {})).result()
        self._node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: NodeID) -> bool:
        """Kill a node: its workers die, its tasks fail/retry, its PG bundles
        reschedule (the chaos-testing seam; reference: NodeKillerActor)."""
        ok = self._scheduler.call("remove_node", node_id).result()
        if node_id in self._node_ids:
            self._node_ids.remove(node_id)
        return ok

    def shutdown(self):
        shutdown()
